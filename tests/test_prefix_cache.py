"""Automatic prefix caching (ISSUE 5): radix-tree KV block reuse with
LRU eviction over the paged serving stack.

Three layers of coverage:

- ``PrefixCache`` unit tests against a bare ``PagedKVCache``: matching,
  donation dedup, the eviction-order invariants (leaf-before-parent,
  refcount>1 never evicted, pinned never evicted, deterministic LRU
  tie-break), allocator reclaim wiring, and the ``prefix.donate`` /
  ``prefix.evict`` fault points leaving zero leaks.
- Server-level tests on the StubModel double (and one real llama):
  auto hits emit BIT-IDENTICAL tokens to cold-cache runs (greedy and
  seeded sampling), prefill savings are asserted via stats/telemetry
  counters (never wall-clock), registered prefixes pin donated pages,
  eviction keeps tiny pools serving, fault injection defers instead of
  failing.
- A chaos suite (``chaos`` marker): 30% fault rates on the prefix
  points during eviction storms — survivors bit-exact, pool balanced,
  same seed same trace.
"""
import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.kv_cache import OutOfPages, PagedKVCache
from paddle_tpu.inference.prefix_cache import PrefixCache
from paddle_tpu.reliability import (CallbackError, CircuitBreaker,
                                    FaultInjector, InjectedFault,
                                    RetryPolicy, faults)
from paddle_tpu.telemetry import MetricRegistry, ServerTelemetry

PG = 4


def _cache(num_pages=17, injector=None):
    kv = PagedKVCache(num_pages=num_pages, page_size=PG, max_slots=4,
                      pages_per_slot=8)
    return PrefixCache(kv, fault_injector=injector), kv


def _donate(cache, kv, ids, extra_pages=0):
    """Simulate a finished slot: alloc the prompt's pages (+ budget),
    fill nothing (host-side tests), donate."""
    ids = np.asarray(ids, np.int32)
    pages = kv.alloc(-(-len(ids) // PG) + extra_pages)
    return cache.donate(ids, pages, len(ids))


def _ids(*toks):
    return np.asarray(toks, np.int32)


class TestRadixTree:
    def test_donate_then_lookup_longest_run(self):
        cache, kv = _cache()
        ids = np.arange(10, dtype=np.int32)          # 2 full pages + tail
        new = _donate(cache, kv, ids)
        assert new == 2 and cache.cached_pages == 2
        assert kv.used_pages() == 2                  # tail page released
        m = cache.lookup(ids, len(ids) - 1)
        assert m.tokens == 8 and len(m.pages) == 2
        # page-granular: an 8-token probe may use at most 1 page (the
        # remainder must keep >= 1 token for the prefill)
        m = cache.lookup(ids[:8], 7)
        assert m.tokens == 4
        # diverging second page -> only the first page matches
        other = np.concatenate([ids[:4], _ids(9, 9, 9, 9)])
        assert cache.lookup(other, 7).tokens == 4
        assert cache.lookup(_ids(5, 5, 5, 5), 3) is None

    def test_donate_dedup_releases_duplicates(self):
        cache, kv = _cache()
        ids = np.arange(8, dtype=np.int32)
        _donate(cache, kv, ids)
        free0 = kv.free_pages()
        new = _donate(cache, kv, ids, extra_pages=3)  # replay + budget
        assert new == 0
        assert cache.dedup_pages_total == 2
        assert kv.free_pages() == free0               # all returned
        assert cache.cached_pages == 2

    def test_eviction_leaf_before_parent(self):
        cache, kv = _cache()
        ids = np.arange(12, dtype=np.int32)           # 3-node chain
        _donate(cache, kv, ids)
        assert cache.evict(1) == 1
        # the deepest page went first; the chain prefix still matches
        assert cache.lookup(ids, 11).tokens == 8
        assert cache.evict(1) == 1
        assert cache.lookup(ids, 11).tokens == 4
        assert kv.used_pages() == 1

    def test_shared_pages_never_evicted(self):
        cache, kv = _cache()
        ids = np.arange(8, dtype=np.int32)
        _donate(cache, kv, ids)
        m = cache.lookup(ids, 8)                      # both pages
        kv.admit_slot(0, 12, shared_pages=m.pages)    # refcount -> 2
        assert cache.evictable_pages() == 0           # chain blocked
        assert cache.evict(10) == 0
        kv.free_slot(0)
        assert cache.evictable_pages() == 2
        assert cache.evict(10) == 2
        assert kv.used_pages() == 0
        # sharing only the chain HEAD still leaves the leaf evictable
        _donate(cache, kv, ids)
        head = cache.lookup(ids, 4)
        kv.admit_slot(0, 8, shared_pages=head.pages)
        assert cache.evictable_pages() == 1
        assert cache.evict(10) == 1                   # the leaf only
        kv.free_slot(0)

    def test_pinned_never_evicted_and_accounting(self):
        cache, kv = _cache()
        ids = np.arange(8, dtype=np.int32)
        _donate(cache, kv, ids)
        run = cache.node_run(ids)
        cache.extend_pinned(ids, run, [])
        assert (cache.pinned_pages, cache.cached_pages) == (2, 0)
        assert cache.evict(10) == 0
        # an unpinned extension under the pinned chain still evicts
        ext = np.arange(16, dtype=np.int32)
        _donate(cache, kv, ext)
        assert cache.cached_pages == 2
        assert cache.evict(10) == 2
        assert cache.pinned_pages == 2 and kv.used_pages() == 2

    def test_lru_order_and_deterministic_tiebreak(self):
        cache, kv = _cache()
        a, b = _ids(1, 1, 1, 1), _ids(2, 2, 2, 2)
        _donate(cache, kv, a)
        _donate(cache, kv, b)                          # b more recent
        cache.use(cache.lookup(a, 5))                  # a now most recent
        assert cache.evict(1) == 1
        assert cache.lookup(b, 5) is None              # LRU: b went first
        assert cache.lookup(a, 5) is not None
        # tie-break: equal last_used falls back to insertion order
        c, d = _ids(3, 3, 3, 3), _ids(4, 4, 4, 4)
        _donate(cache, kv, c)
        _donate(cache, kv, d)
        for key, node in cache._root.children.items():
            node.last_used = 7
        evicted_first = min(cache._root.children.values(),
                            key=lambda n: n.seq)
        cache.evict(1)
        assert cache.lookup(
            np.asarray(evicted_first.key, np.int32), 5) is None

    def test_protect_shields_nodes_across_reclaim(self):
        cache, kv = _cache(num_pages=6)                # 5 usable
        ids = np.arange(8, dtype=np.int32)
        _donate(cache, kv, ids)
        run = cache.node_run(ids)
        cache.protect(run)
        assert cache.evictable_pages() == 0
        assert cache.evict(10) == 0
        cache.protect(())
        assert cache.evictable_pages() == 2

    def test_reclaimer_wired_into_alloc(self):
        cache, kv = _cache(num_pages=6)                # 5 usable
        kv.reclaimer = cache.evict
        _donate(cache, kv, np.arange(12, dtype=np.int32))
        assert kv.free_pages() == 2
        pages = kv.alloc(4)                            # forces 2 evictions
        assert len(pages) == 4
        assert cache.evicted_pages_total == 2
        kv.release(pages)
        with pytest.raises(OutOfPages):
            kv.alloc(6)                                # > usable, even evicting

    def test_donate_fault_leaves_tree_and_refcounts_untouched(self):
        fi = FaultInjector(seed=3).on(faults.PREFIX_DONATE, schedule=[0])
        cache, kv = _cache(injector=fi)
        ids = np.arange(8, dtype=np.int32)
        pages = kv.alloc(2)
        with pytest.raises(InjectedFault):
            cache.donate(ids, pages, len(ids))
        assert cache.cached_pages == 0 and cache.lookup(ids, 7) is None
        kv.release(pages)                              # caller's fallback
        assert kv.used_pages() == 0
        _donate(cache, kv, ids)                        # next visit clean
        assert cache.cached_pages == 2

    def test_evict_fault_aborts_sweep_cleanly(self):
        fi = FaultInjector(seed=3).on(faults.PREFIX_EVICT, schedule=[0])
        cache, kv = _cache(injector=fi)
        _donate(cache, kv, np.arange(8, dtype=np.int32))
        with pytest.raises(InjectedFault):
            cache.evict(1)
        assert cache.cached_pages == 2                 # nothing removed
        assert cache.evict(1) == 1                     # next sweep works

    def test_stats_snapshot(self):
        cache, kv = _cache()
        _donate(cache, kv, np.arange(8, dtype=np.int32))
        _donate(cache, kv, np.arange(8, dtype=np.int32))
        cache.evict(1)
        s = cache.stats()
        assert s["donated_pages_total"] == 2
        assert s["dedup_pages_total"] == 2
        assert s["evicted_pages_total"] == 1
        assert s["cached_pages"] == 1 and s["pinned_pages"] == 0


# ---------------------------------------------------------------- server


def _srv(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 32)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 4)
    return ContinuousBatchingServer(StubModel(), **kw)


def _usable(srv):
    return srv._kv.num_pages - 1


class TestAutoPrefixServer:
    def test_auto_hit_parity_and_counted_savings(self):
        """Acceptance: a prompt extending a previously-served prompt
        emits bit-identical tokens to a cold run, and the saved prefill
        work shows up in stats + telemetry counters."""
        tele = ServerTelemetry(registry=MetricRegistry())
        srv = _srv(telemetry=tele)
        a = np.arange(12, dtype=np.int32) % 16
        b = np.concatenate([a, _ids(3, 1)])
        ra = srv.submit(a, max_new_tokens=4)
        srv.run()
        rb = srv.submit(b, max_new_tokens=5)
        out = srv.run()[rb]
        np.testing.assert_array_equal(out, stub_tokens(b, 5))
        cold = _srv()
        rc = cold.submit(b, max_new_tokens=5)
        np.testing.assert_array_equal(cold.run()[rc], out)
        assert srv.stats["prefix_auto_hits"] == 1
        assert srv.stats["prefix_auto_hit_tokens"] == 12
        assert srv.stats["prefill_tokens"] == 12 + 2   # vs 12 + 14 cold
        assert cold.stats["prefill_tokens"] == 14
        reg = tele.registry
        pfx = reg.get("serving_prefix_cache_total")
        assert pfx.labels(result="auto_hit").value == 1.0
        assert pfx.labels(result="auto_miss").value == 1.0
        assert reg.get("kv_prefix_donated_pages_total").value == 3.0
        assert reg.get("kv_prefix_cached_pages").value == 3.0
        assert reg.get("kv_prefix_hit_tokens").value == 12.0
        tok = reg.get("serving_tokens_total")
        assert tok.labels(kind="prefill").value == 14.0
        assert tok.labels(kind="prefix_hit").value == 12.0

    def test_shared_system_prompt_workload_saves_prefill(self):
        """Acceptance: N requests sharing a system prompt measurably
        reduce prefill page writes vs auto_prefix_cache=False —
        asserted via counters, not wall-clock."""
        rng = np.random.default_rng(7)
        system = rng.integers(0, 16, (8,)).astype(np.int32)
        prompts = [np.concatenate(
            [system, rng.integers(0, 16, (3,)).astype(np.int32)])
            for _ in range(6)]

        def run(auto):
            srv = _srv(max_slots=1, auto_prefix_cache=auto)
            outs = {}
            for p in prompts:
                rid = srv.submit(p, max_new_tokens=4)
                outs[rid] = srv.run()[rid]
            return srv, list(outs.values())

        on_srv, on_outs = run(True)
        off_srv, off_outs = run(False)
        for got, want, p in zip(on_outs, off_outs, prompts):
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(got, stub_tokens(p, 4))
        # every request after the first hits the shared 8-token page run
        assert on_srv.stats["prefix_auto_hits"] == 5
        assert on_srv.stats["prefix_auto_hit_tokens"] == 5 * 8
        assert on_srv.stats["prefill_tokens"] == \
            off_srv.stats["prefill_tokens"] - 5 * 8
        assert off_srv.stats["prefix_auto_hits"] == 0
        assert off_srv.pool_balance() == (_usable(off_srv), 0, 0, 0)

    def test_sampled_auto_hit_parity_seeded(self):
        warm = _srv(do_sample=True, temperature=1.2, top_k=5, seed=0)
        cold = _srv(do_sample=True, temperature=1.2, top_k=5, seed=0)
        a = np.arange(8, dtype=np.int32)
        b = np.concatenate([a, _ids(2, 7, 1)])
        warm.submit(a, max_new_tokens=4, seed=11)
        warm.run()
        rw = warm.submit(b, max_new_tokens=6, seed=99)
        rc = cold.submit(b, max_new_tokens=6, seed=99)
        np.testing.assert_array_equal(warm.run()[rw], cold.run()[rc])
        assert warm.stats["prefix_auto_hits"] == 1

    def test_identical_prompt_replay_dedups_pages(self):
        srv = _srv()
        p = np.arange(12, dtype=np.int32) % 16
        for _ in range(3):
            rid = srv.submit(p, max_new_tokens=4)
            np.testing.assert_array_equal(srv.run()[rid],
                                          stub_tokens(p, 4))
        free, live, pinned, cached = srv.pool_balance()
        assert (live, pinned, cached) == (0, 0, 3)     # stored ONCE
        assert free == _usable(srv) - 3
        assert srv.stats["prefix_auto_hits"] == 2

    def test_eviction_keeps_tiny_pool_serving(self):
        rng = np.random.default_rng(0)
        srv = _srv(num_pages=9)                        # 8 usable pages
        seen_evictions = 0
        for _ in range(6):
            p = rng.integers(0, 16, (8,)).astype(np.int32)
            rid = srv.submit(p, max_new_tokens=4)      # extent 12 -> 3 pages
            np.testing.assert_array_equal(srv.run()[rid],
                                          stub_tokens(p, 4))
            free, live, pinned, cached = srv.pool_balance()
            assert live == 0
            assert free + pinned + cached == 8
        assert srv._prefix.evicted_pages_total > 0     # pressure hit LRU
        assert srv._prefix.cached_pages > 0            # cache survives

    def test_register_prefix_adopts_and_pins_donated_pages(self):
        srv = _srv()
        p = np.arange(8, dtype=np.int32)
        srv.submit(p, max_new_tokens=4)
        srv.run()
        assert srv.pool_balance() == (_usable(srv) - 2, 0, 0, 2)
        used0 = srv._kv.used_pages()
        assert srv.register_prefix(p) == 8
        # adopted, not re-allocated: same pages, now pinned
        assert srv._kv.used_pages() == used0
        assert srv.pool_balance() == (_usable(srv) - 2, 0, 2, 0)
        # pinned entries survive an eviction storm that empties the rest
        rng = np.random.default_rng(1)
        for _ in range(8):
            q = rng.integers(0, 16, (8,)).astype(np.int32)
            srv.submit(q, max_new_tokens=4)
            srv.run()
        assert srv.pool_balance()[2] == 2              # still pinned
        rid = srv.submit(np.concatenate([p, _ids(1, 2)]),
                         max_new_tokens=4)
        srv.run()
        assert srv.stats["prefix_hit_tokens"] >= 8     # registered hit

    def test_evict_fault_defers_admission_not_fails(self):
        fi = FaultInjector(seed=1).on(faults.PREFIX_EVICT, schedule=[0])
        srv = _srv(max_slots=1, num_pages=9, fault_injector=fi)
        rng = np.random.default_rng(2)
        a = rng.integers(0, 16, (12,)).astype(np.int32)
        srv.submit(a, max_new_tokens=4)
        srv.run()                                      # leaves 3 cached
        b = rng.integers(0, 16, (20,)).astype(np.int32)  # needs eviction
        rb = srv.submit(b, max_new_tokens=4)
        out = srv.run()
        np.testing.assert_array_equal(out[rb], stub_tokens(b, 4))
        assert fi.fired(faults.PREFIX_EVICT) == 1      # sweep 0 aborted
        assert rb not in srv.failures                  # deferred, not failed
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and free + cached == 8

    def test_donate_fault_frees_pages_instead_of_caching(self):
        fi = FaultInjector(seed=1).on(faults.PREFIX_DONATE,
                                      probability=1.0)
        srv = _srv(fault_injector=fi)
        p = np.arange(12, dtype=np.int32) % 16
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 4))
        assert srv.pool_balance() == (_usable(srv), 0, 0, 0)  # no leak
        assert fi.fired(faults.PREFIX_DONATE) == 1
        assert srv.stats["prefix_auto_hits"] == 0

    def test_auto_off_keeps_pr1_semantics(self):
        srv = _srv(auto_prefix_cache=False)
        p = np.arange(12, dtype=np.int32) % 16
        srv.submit(p, max_new_tokens=4)
        srv.run()
        assert srv.pool_balance() == (_usable(srv), 0, 0, 0)
        rid = srv.submit(np.concatenate([p, _ids(1)]), max_new_tokens=4)
        srv.run()
        assert srv.stats["prefix_auto_hits"] == 0
        assert srv.stats["prefix_hit_tokens"] == 0

    def test_chunked_prefill_pad_guard_trims_unsafe_match(self):
        """DENSE prefill mode: a tree hit whose remainder would
        chunk-pad past max_cache_len is trimmed (here: to nothing)
        instead of overflowing the cache rows — the submit-time bound
        only knew the hits registered THEN (ADVICE r5 #2 lineage)."""
        rng = np.random.default_rng(3)
        srv = _srv(max_slots=1, prefill_chunk=8, prefill_mode="dense")
        donor = rng.integers(0, 16, (12,)).astype(np.int32)
        srv.submit(donor, max_new_tokens=4)
        srv.run()
        # shares exactly one page with the donor; remainder 25 tokens
        # would pad to 32 rows -> 4 + 32 > 32 overflows, so no auto hit
        p = np.concatenate([donor[:4],
                            rng.integers(0, 16, (25,)).astype(np.int32)])
        rid = srv.submit(p, max_new_tokens=3)
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 3))
        assert srv.stats["prefix_auto_hits"] == 0

    def test_ragged_mode_never_pads_so_match_survives(self):
        """RAGGED prefill mode (ISSUE 6 satellite): the same workload
        KEEPS the hit — ragged remainders are chunked by the per-tick
        token budget at arbitrary cut points, never padded, so the
        chunk-pad trim (and the submit-time pad bound) do not apply."""
        rng = np.random.default_rng(3)
        srv = _srv(max_slots=1, prefill_chunk=8)     # ragged default
        assert srv.prefill_mode == "ragged"
        donor = rng.integers(0, 16, (12,)).astype(np.int32)
        srv.submit(donor, max_new_tokens=4)
        srv.run()
        p = np.concatenate([donor[:4],
                            rng.integers(0, 16, (25,)).astype(np.int32)])
        rid = srv.submit(p, max_new_tokens=3)        # 29 + 3 fits 32
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 3))
        assert srv.stats["prefix_auto_hits"] == 1
        assert srv.stats["prefix_auto_hit_tokens"] == 4

    def test_llama_auto_hit_matches_solo_generate(self):
        """Real-model acceptance: the auto hit's gather-seeded remainder
        prefill + page-shared decode is bit-identical to a solo
        generate()."""
        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(21)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(4)
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged", page_size=8)
        donor = rng.integers(0, 256, (12,)).astype(np.int32)
        srv.submit(donor, max_new_tokens=4)
        srv.run()
        p = np.concatenate([donor[:8],
                            rng.integers(0, 256, (3,)).astype(np.int32)])
        rid = srv.submit(p, max_new_tokens=6)
        out = srv.run()[rid]
        want = model.generate(pt.to_tensor(p[None]), max_new_tokens=6,
                              max_cache_len=64).numpy()[0, len(p):]
        np.testing.assert_array_equal(out, want)
        assert srv.stats["prefix_auto_hits"] == 1
        assert srv.stats["prefix_auto_hit_tokens"] == 8


# ----------------------------------------------------------------- chaos


@pytest.mark.chaos
class TestEvictionChaos:
    def _injector(self, seed):
        return (FaultInjector(seed=seed)
                .on(faults.PREFILL, probability=0.15)
                .on(faults.DECODE_TICK, probability=0.1)
                .on(faults.PAGE_ALLOC, probability=0.1)
                .on(faults.PREFIX_EVICT, probability=0.3)
                .on(faults.PREFIX_DONATE, probability=0.3))

    def _srv(self, fi, **kw):
        kw.setdefault("max_slots", 2)
        kw.setdefault("max_cache_len", 32)
        kw.setdefault("cache_backend", "paged")
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 11)       # 10 usable: constant pressure
        kw.setdefault("retry_policy", RetryPolicy(base_delay_s=0.0,
                                                  jitter=0.0))
        kw.setdefault("breaker", CircuitBreaker(failure_threshold=10_000))
        return ContinuousBatchingServer(StubModel(), fault_injector=fi,
                                        **kw)

    def _drive(self, srv, max_ticks=5000):
        ticks = 0
        while True:
            with srv._lock:
                busy = srv._busy_locked()   # incl. mid-prefill slots
            if not busy:
                return
            try:
                srv.step()
            except CallbackError:
                pass
            except Exception:
                pass                         # transient tick fault: retry
            ticks += 1
            assert ticks < max_ticks, "chaos drive did not converge"

    def _workload(self, seed=5):
        rng = np.random.default_rng(seed)
        system = rng.integers(0, 16, (8,)).astype(np.int32)
        return [np.concatenate(
            [system, rng.integers(0, 16, (int(n),)).astype(np.int32)])
            for n in rng.integers(1, 6, (16,))]

    def test_eviction_storm_zero_leaks(self):
        """Acceptance: 30% fault rate on prefix.evict/donate during an
        eviction storm — survivors bit-exact, pool_balance reports zero
        leaked pages."""
        fi = self._injector(seed=606)
        srv = self._srv(fi)
        prompts = self._workload()
        rids = [srv.submit(p, max_new_tokens=4) for p in prompts]
        self._drive(srv)
        outs = srv._results
        served = 0
        for rid, p in zip(rids, prompts):
            if rid in outs:
                served += 1
                np.testing.assert_array_equal(outs[rid],
                                              stub_tokens(p, 4))
        assert served > 0
        assert fi.fired(faults.PREFIX_EVICT) \
            + fi.fired(faults.PREFIX_DONATE) > 0, "prefix chaos idle"
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0, f"leaked {live} pages"
        assert free + pinned + cached == srv._kv.num_pages - 1

    def test_eviction_storm_with_pinned_prefix(self):
        """Pinned pages survive the storm; donated pages churn around
        them; books stay balanced."""
        fi = self._injector(seed=77)
        fi.disarm()
        srv = self._srv(fi)
        system = self._workload()[0][:8]
        srv.register_prefix(system)
        fi.arm()
        for p in self._workload(seed=9):
            srv.submit(p, max_new_tokens=3)
        self._drive(srv)
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and pinned == 2
        assert free + pinned + cached == srv._kv.num_pages - 1

    def test_same_seed_identical_trace_and_cache_state(self):
        def run_once():
            fi = self._injector(seed=4242)
            srv = self._srv(fi)
            for p in self._workload(seed=11):
                srv.submit(p, max_new_tokens=4)
            self._drive(srv)
            results = {r: tuple(int(x) for x in v)
                       for r, v in srv._results.items()}
            fails = {r: type(e).__name__
                     for r, e in srv.failures.items()}
            return (fi.trace, results, fails, srv.pool_balance(),
                    srv._prefix.stats())

        a, b = run_once(), run_once()
        assert a == b
        assert a[0], "deterministic run injected nothing"


# ----------------------------------------------------------------- bench


@pytest.mark.slow
@pytest.mark.bench
class TestPrefixCacheBenchGuard:
    def test_shared_prompt_hit_rate_and_savings(self):
        """Counter-based guard for benchmarks/prefix_cache_bench.py:
        the shared-system-prompt workload must hit on every follow-up
        request and cut prefill tokens by the shared page run."""
        rng = np.random.default_rng(0)
        system = rng.integers(0, 16, (16,)).astype(np.int32)
        prompts = [np.concatenate(
            [system, rng.integers(0, 16, (4,)).astype(np.int32)])
            for _ in range(8)]
        srv = _srv(max_slots=1, max_cache_len=64, page_size=4)
        for p in prompts:
            rid = srv.submit(p, max_new_tokens=8)
            np.testing.assert_array_equal(srv.run()[rid],
                                          stub_tokens(p, 8))
        hits = srv.stats["prefix_auto_hits"]
        assert hits == len(prompts) - 1
        assert srv.stats["prefix_auto_hit_tokens"] == hits * 16
