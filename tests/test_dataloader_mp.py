"""Multiprocess DataLoader: correctness, shared memory, worker scaling
(reference pattern: dataloader_iter.py multiprocess tests +
test_dataloader_* throughput behavior)."""
import time

import numpy as np
import pytest

from paddle_tpu.io.dataloader import (DataLoader, Dataset, get_worker_info)


class ArrayDataset(Dataset):
    def __init__(self, n=32, dim=8):
        self.x = np.arange(n * dim, dtype=np.float32).reshape(n, dim)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i]


class SlowDataset(ArrayDataset):
    """CPU-burning transform: multiprocess workers must parallelize it
    (a GIL-bound thread pool cannot)."""

    def __getitem__(self, i):
        deadline = time.perf_counter() + 0.02
        acc = 0.0
        while time.perf_counter() < deadline:
            acc += float(np.sum(self.x[i] * self.x[i]))
        return self.x[i] + (acc * 0.0)


class DictDataset(ArrayDataset):
    def __getitem__(self, i):
        return {"x": self.x[i], "y": np.int64(i)}


class WorkerProbeDataset(ArrayDataset):
    def __getitem__(self, i):
        info = get_worker_info()
        wid = -1 if info is None else info.id
        return np.array([i, wid], np.int64)


def _epoch(loader):
    return [np.asarray(b) for b in loader]


@pytest.mark.parametrize("shm", [False, True])
def test_mp_matches_single_process(shm):
    ds = ArrayDataset(32, 8)
    ref = _epoch(DataLoader(ds, batch_size=4, num_workers=0))
    got = _epoch(DataLoader(ds, batch_size=4, num_workers=3,
                            use_shared_memory=shm))
    assert len(ref) == len(got) == 8
    for r, g in zip(ref, got):
        np.testing.assert_allclose(r, g)


def test_mp_dict_batches():
    ds = DictDataset(16, 4)
    out = list(DataLoader(ds, batch_size=4, num_workers=2))
    assert len(out) == 4
    for bi, b in enumerate(out):
        np.testing.assert_array_equal(
            np.asarray(b["y"]), np.arange(bi * 4, bi * 4 + 4))


def test_workers_really_run_in_subprocesses():
    ds = WorkerProbeDataset(12, 2)
    out = list(DataLoader(ds, batch_size=3, num_workers=2))
    wids = {int(row[1]) for b in out for row in np.asarray(b)}
    assert wids <= {0, 1} and len(wids) >= 1
    assert -1 not in wids, "samples were loaded in the parent process"


def test_persistent_workers_two_epochs():
    ds = ArrayDataset(16, 4)
    dl = DataLoader(ds, batch_size=4, num_workers=2,
                    persistent_workers=True)
    e1 = _epoch(dl)
    workers_after_1 = list(dl._workers)
    e2 = _epoch(dl)
    assert all(p.is_alive() for p in workers_after_1)
    for a, b in zip(e1, e2):
        np.testing.assert_allclose(a, b)
    dl._shutdown_workers()


def test_persistent_early_break_no_stale_batches():
    # review regression: break mid-epoch, then a full epoch — the second
    # epoch must not be satisfied by the abandoned epoch's results
    class Tagged(ArrayDataset):
        pass

    ds = ArrayDataset(16, 4)
    dl = DataLoader(ds, batch_size=4, num_workers=2,
                    persistent_workers=True, shuffle=False)
    it = iter(dl)
    next(it)     # abandon after one batch
    del it
    got = _epoch(dl)
    ref = _epoch(DataLoader(ds, batch_size=4, num_workers=0))
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r)
    dl._shutdown_workers()


def test_bounded_prefetch_window():
    ds = ArrayDataset(64, 2)
    dl = DataLoader(ds, batch_size=2, num_workers=2, prefetch_factor=2)
    it = iter(dl)
    next(it)
    # after one consumed batch only ~window batches may be dispatched
    submitted = sum(q.qsize() for q in dl._index_queues)
    assert submitted <= 2 * max(2, dl.prefetch_factor) * dl.num_workers
    list(it)  # finish cleanly


def test_worker_exception_propagates():
    class Boom(ArrayDataset):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom at 5")
            return self.x[i]

    dl = DataLoader(Boom(8, 2), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)


def test_workers_scale_slow_transform():
    """VERDICT done-criterion: multiprocess workers must speed up a
    CPU-bound per-sample transform (threads cannot, GIL)."""
    ds = SlowDataset(24, 8)

    t0 = time.perf_counter()
    _epoch(DataLoader(ds, batch_size=4, num_workers=0))
    t_serial = time.perf_counter() - t0

    dl = DataLoader(ds, batch_size=4, num_workers=4,
                    persistent_workers=True)
    _epoch(dl)                       # warm epoch pays worker startup
    t0 = time.perf_counter()
    _epoch(dl)                       # steady state
    t_mp = time.perf_counter() - t0
    dl._shutdown_workers()

    # 24 samples x 20ms = 480ms serial; 4 procs must beat serial. The
    # CI box has ONE core, so the attainable speedup comes from
    # pipelining, not real parallelism, and background load adds noise —
    # require a clear win, not an exact ratio.
    assert t_mp < t_serial * 0.85, (t_serial, t_mp)


# ---------------------------------------------------- native ring transport

def test_native_ring_transport_round_trips():
    """use_native_ring=True routes worker results through the C
    shared-memory SPSC ring (runtime csrc/shm_ring.cc) — same batches,
    same order as the queue transport."""
    from paddle_tpu.io.dataloader import DataLoader

    class DS:
        def __len__(self):
            return 24

        def __getitem__(self, i):
            return np.full((4,), float(i), np.float32)

    dl = DataLoader(DS(), batch_size=4, num_workers=2, shuffle=False,
                    use_native_ring=True)
    got = [b for b in dl]
    dl._shutdown_workers()
    assert len(got) == 6
    for k, b in enumerate(got):
        want = np.stack([np.full((4,), float(4 * k + j), np.float32)
                         for j in range(4)])
        np.testing.assert_allclose(np.asarray(b), want)


def test_native_ring_oversized_batch_falls_back_to_shm_refs():
    """A batch bigger than the ring slot parks its arrays in their own
    shm segments and sends light refs through the ring."""
    from paddle_tpu.io.dataloader import DataLoader

    class BigDS:
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.full((1 << 18,), float(i), np.float32)  # 1 MB each

    # 1 MB slots; batch of 2 = 2 MB payload -> overflow path
    dl = DataLoader(BigDS(), batch_size=2, num_workers=1, shuffle=False,
                    use_native_ring=True, ring_slot_mb=1)
    got = [np.asarray(b) for b in dl]
    dl._shutdown_workers()
    assert len(got) == 2 and got[0].shape == (2, 1 << 18)
    np.testing.assert_allclose(got[0][0], 0.0)
    np.testing.assert_allclose(got[1][1], 3.0)


def test_native_ring_object_heavy_batch_reports_instead_of_dying():
    """A batch that cannot shrink below the slot (no big ndarrays)
    surfaces a clear error; the worker survives."""
    import pytest
    from paddle_tpu.io.dataloader import DataLoader

    class ObjDS:
        def __len__(self):
            return 2

        def __getitem__(self, i):
            return ["x" * 500_000]          # strings: _tree_to_shm no-op

    def collate(items):
        return sum(items, [])

    # tiny slots: the pickled strings can never fit
    dl = DataLoader(ObjDS(), batch_size=2, num_workers=1, shuffle=False,
                    use_native_ring=True, ring_slot_mb=0)
    dl.ring_slot = 4096
    with pytest.raises(RuntimeError, match="ring slot"):
        list(dl)
    dl._shutdown_workers()


def test_resume_iter_skips_without_fetching():
    """Mid-epoch resume support: the skipped prefix must consume only
    the sampler's index lists — zero __getitem__/collate work — so
    resume cost is independent of the position in the epoch."""
    seen = []

    class ProbeDataset(ArrayDataset):
        def __getitem__(self, i):
            seen.append(i)
            return super().__getitem__(i)

    dl = DataLoader(ProbeDataset(n=32), batch_size=4, shuffle=False,
                    num_workers=0)
    full = [b for b in dl]
    seen.clear()
    resumed = list(dl.resume_iter(5))
    assert len(resumed) == 3
    for got, want in zip(resumed, full[5:]):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert min(seen) == 20                  # nothing before batch 5 fetched
    # skip=0 and skip-past-the-end degenerate cleanly
    assert len(list(dl.resume_iter(0))) == 8
    assert list(dl.resume_iter(99)) == []
