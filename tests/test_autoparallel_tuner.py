"""Parallel-layout tuner (reference auto_parallel/tuner + cost model)."""
import numpy as np

from paddle_tpu.parallel.auto_parallel.tuner import (
    ClusterSpec, ModelSpec, ParallelTuner, RuleBasedTuner, tune)


def test_factorization_coverage():
    t = ParallelTuner(ClusterSpec(n_chips=8), ModelSpec(n_params=1e8))
    cands = t.tune(top_k=100)
    assert all(c.dp * c.mp * c.pp * c.sharding == 8 for c in cands)
    assert len({(c.dp, c.mp, c.pp, c.sharding) for c in cands}) == len(cands)


def test_small_model_prefers_pure_dp():
    # a tiny model has no memory pressure and no TP need: dp-only wins
    # (no comm for tp, no bubble for pp; only the cheap grad allreduce)
    best = tune(ClusterSpec(n_chips=8), ModelSpec(
        n_params=1e8, batch_tokens=1 << 20), top_k=1)[0]
    assert best.pp == 1 and best.mp == 1


def test_big_model_requires_model_parallel():
    # 70B at 14 bytes/param (weights+grads+opt) is ~1TB of state: pure
    # dp on 64 chips is infeasible and the tuner must split the model
    cl = ClusterSpec(n_chips=64, hbm_bytes=95e9)
    md = ModelSpec(n_params=70e9, n_layers=80, hidden=8192)
    t = ParallelTuner(cl, md)
    pure_dp = t._score(64, 1, 1, 1)
    assert not pure_dp.feasible
    best = t.tune(top_k=1)[0]
    assert best.feasible
    assert best.mp * best.pp * best.sharding > 1


def test_bubble_fraction_decreases_with_microbatches():
    cl, md = ClusterSpec(n_chips=8), ModelSpec(n_params=1e9)
    few = ParallelTuner(cl, md, micro_batches=2)._score(1, 1, 8, 1)
    many = ParallelTuner(cl, md, micro_batches=32)._score(1, 1, 8, 1)
    assert many.bubble_fraction < few.bubble_fraction


def test_rule_based_keeps_mp_in_host():
    cl = ClusterSpec(n_chips=16, chips_per_host=4, hbm_bytes=30e9)
    md = ModelSpec(n_params=20e9)
    best = RuleBasedTuner(cl, md).tune(top_k=1)[0]
    # the winning config must keep tensor parallelism inside one host
    assert best.mp <= 4

    # and the tie-break is live: among configs with (near-)equal step
    # time, a same-cost mp>host config must not outrank an mp<=host one
    all_ranked = RuleBasedTuner(cl, md).tune(top_k=None)
    times = [round(c.step_time, 6) for c in all_ranked]
    first_time = times[0]
    same_cost = [c for c in all_ranked
                 if round(c.step_time, 6) == first_time]
    if any(c.mp > 4 for c in same_cost):
        assert same_cost[0].mp <= 4


def test_strategy_degrees_consumable():
    best = tune(ClusterSpec(n_chips=8), ModelSpec(n_params=1e9),
                top_k=1)[0]
    d = best.degrees
    assert set(d) == {"dp_degree", "mp_degree", "pp_degree",
                     "sharding_degree"}
    assert int(np.prod(list(d.values()))) == 8


def test_cost_model_separates_matmul_and_lookup():
    """cost_model.measure_program (VERDICT r3 #8): real per-op FLOPs /
    bytes classification, not output-element counting."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.static as static
    from paddle_tpu.cost_model import CostModel

    main = static.Program()
    with static.program_guard(main):
        w = static.create_parameter([512, 512], name="w_mm")
        x = static.data("x", [64, 512])
        y = x @ w                      # 2*64*512*512 matmul flops
        tbl = static.create_parameter([1000, 64], name="tbl")
        ids = static.data("ids", [256], dtype="int64")
        e = pt.nn.functional.embedding(ids, tbl)
        z = y.sum() + e.sum()
    static.normalize_program(main, [x, ids], [z])
    meas = CostModel().measure_program(main)
    assert meas["matmul_flops"] >= 2 * 64 * 512 * 512
    assert meas["lookup_bytes"] > 0
    assert 0 < meas["matmul_frac"] <= 1


def test_tuner_prefers_tp_for_matmul_bound_program():
    import paddle_tpu.static as static
    from paddle_tpu.parallel.auto_parallel.tuner import (ClusterSpec,
                                                         tune_for_program)

    main = static.Program()
    with static.program_guard(main):
        w1 = static.create_parameter([4096, 4096], name="w1")
        w2 = static.create_parameter([4096, 4096], name="w2")
        x = static.data("x", [8, 4096])
        h = (x @ w1) @ w2              # params >> activations
    static.normalize_program(main, [x], [h])
    top = tune_for_program(main, ClusterSpec(n_chips=8),
                           rule_based=False)[0]
    assert top.mp > 1, f"matmul-bound should pick TP, got {top.degrees}"


def test_tuner_prefers_dp_for_embedding_bound_program():
    import paddle_tpu as pt
    import paddle_tpu.static as static
    from paddle_tpu.parallel.auto_parallel.tuner import (ClusterSpec,
                                                         tune_for_program)

    main = static.Program()
    with static.program_guard(main):
        tbl = static.create_parameter([200000, 64], name="tbl2")
        ids = static.data("ids", [65536], dtype="int64")
        e = pt.nn.functional.embedding(ids, tbl)
        out = e * 2.0
    static.normalize_program(main, [ids], [out])
    top = tune_for_program(main, ClusterSpec(n_chips=8),
                           rule_based=False)[0]
    assert top.mp == 1, f"embedding-bound should avoid TP, {top.degrees}"
    assert top.dp * top.sharding * top.pp == 8
