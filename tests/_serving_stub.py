"""A serving-contract test double for ContinuousBatchingServer.

Reliability and chaos tests exercise HOST-side machinery — queues,
deadlines, supervision, page accounting — where real transformer
numerics only add compile time and noise. ``StubModel`` implements
exactly the decode-bundle contract the server consumes
(``_decode_bundle`` + ``_run_prefill``, dense AND paged) with a closed
-form token recurrence, so every test can predict full outputs:

    first  = (7 * prompt[-1] + len(prompt)) % V          (prefill)
    tok_k+1 = (7 * tok_k + t_k + 1) % V,  t_k = T, T+1, ...

``stub_tokens(prompt, n)`` is the oracle. Prefill writes token values
into the cache rows it covers, so page fills / prefix sharing move real
data; decode steps pass caches through untouched (logits depend only on
(token, position), which is what makes the oracle exact). The paged
bundle carries the ragged-prefill entry point (element 5, ISSUE 6)
with the same write-token-values semantics, so the ragged scheduler's
chunk packing, null-redirects and prefix-offset resumes are exercised
against the oracle too.
"""
import numpy as np

import jax
import jax.numpy as jnp

V = 16


def stub_tokens(prompt, n):
    """The n new tokens a StubModel-backed server must emit."""
    prompt = np.asarray(prompt).reshape(-1)
    T = len(prompt)
    toks = [(7 * int(prompt[-1]) + T) % V]
    t = T
    while len(toks) < n:
        toks.append((7 * toks[-1] + t + 1) % V)
        t += 1
    return np.asarray(toks[:n], np.int32)


class StubModel:
    L, H, HD = 1, 1, 2           # layers / kv heads / head dim
    V = V

    def _decode_bundle(self, max_cache_len, weight_dtype=None, mesh=None,
                       cache_dtype=None, cache_backend="dense",
                       page_size=None, num_pages=None):
        L, h, hd, vocab = self.L, self.H, self.HD, self.V
        C = int(max_cache_len)

        if cache_backend == "paged":
            pg = int(page_size)
            maxp = C // pg

            def init_caches(batch):
                shape = (L, int(num_pages), pg, h, hd)
                return {"pool": {"k": jnp.zeros(shape, jnp.float32),
                                 "v": jnp.zeros(shape, jnp.float32)},
                        "bt": jnp.zeros((batch, maxp), jnp.int32)}
        else:
            def init_caches(batch):
                shape = (L, batch, C, h, hd)
                return {"k": jnp.zeros(shape, jnp.float32),
                        "v": jnp.zeros(shape, jnp.float32)}

        def embed_fn(tok, t):
            return jnp.stack([tok.astype(jnp.float32),
                              t.astype(jnp.float32)], axis=-1)

        def step_fn(x, caches, t):
            return x, caches

        def head_fn(out):
            tok = out[..., 0].astype(jnp.int32)
            t = out[..., 1].astype(jnp.int32)
            nxt = (7 * tok + t + 1) % vocab
            return jax.nn.one_hot(nxt, vocab, dtype=jnp.float32) * 10.0

        if cache_backend == "paged":
            def ragged_prefill(tokens, t0, caches, out_idx):
                """Ragged-prefill contract (paged bundle element 5):
                tokens [S, C] packed chunks, t0 [S] start positions
                (idle slots carry t0 = max_cache_len — every write
                null-redirects zeroed), out_idx [S] row of each slot's
                last prompt token. Writes token VALUES into pool pages
                (page fills move real data, like _run_prefill) and
                returns the oracle's next-token logits per slot."""
                pool, bt = caches["pool"], caches["bt"]
                S, Cc = tokens.shape
                pos = t0[:, None] + jnp.arange(Cc, dtype=jnp.int32)[None]
                pidx = pos // pg
                oob = pidx >= maxp
                page = jnp.where(
                    oob, 0, jnp.take_along_axis(
                        bt, jnp.minimum(pidx, maxp - 1), axis=1))
                vals = jnp.where(oob, 0.0, tokens.astype(jnp.float32))
                n = S * Cc
                flat = jnp.broadcast_to(
                    vals.reshape(n)[:, None, None], (n, h, hd))
                fp, fo = page.reshape(n), (pos % pg).reshape(n)
                pool = {"k": pool["k"].at[:, fp, fo].set(flat[None]),
                        "v": pool["v"].at[:, fp, fo].set(flat[None])}
                last_tok = jnp.take_along_axis(
                    tokens, out_idx[:, None], axis=1)[:, 0]
                last_pos = t0 + out_idx
                nxt = (7 * last_tok + last_pos + 1) % vocab
                logits = jax.nn.one_hot(nxt, vocab,
                                        dtype=jnp.float32) * 10.0
                return logits, dict(caches, pool=pool)

            def fused_tick(tokens, t0, last, dec, caches, out_idx,
                           bt_live, ss, sp):
                """Fused-tick contract (paged bundle element 6,
                ISSUE 14): one launch carries every slot's work —
                prefill chunks, single decode rows (column 0, dec=1),
                idle slots (last=-1, all writes null-redirect
                zeroed). ``bt_live`` is the block tables sliced to
                the live page width; the schedule args ride along
                unused (the stub has no kernel to drive). Writes
                token VALUES into pool pages like the ragged entry
                and returns the oracle's next-token logits at each
                slot's ``out_idx`` row."""
                pool = caches["pool"]
                S, Cc = tokens.shape
                W = bt_live.shape[1]
                pos = t0[:, None] + jnp.arange(Cc, dtype=jnp.int32)[None]
                pidx = pos // pg
                oob = (pidx >= W) | (pos > last[:, None])
                page = jnp.where(
                    oob, 0, jnp.take_along_axis(
                        bt_live, jnp.minimum(pidx, W - 1), axis=1))
                vals = jnp.where(oob, 0.0, tokens.astype(jnp.float32))
                n = S * Cc
                flat = jnp.broadcast_to(
                    vals.reshape(n)[:, None, None], (n, h, hd))
                fp, fo = page.reshape(n), (pos % pg).reshape(n)
                pool = {"k": pool["k"].at[:, fp, fo].set(flat[None]),
                        "v": pool["v"].at[:, fp, fo].set(flat[None])}
                last_tok = jnp.take_along_axis(
                    tokens, out_idx[:, None], axis=1)[:, 0]
                last_pos = t0 + out_idx
                nxt = (7 * last_tok + last_pos + 1) % vocab
                logits = jax.nn.one_hot(nxt, vocab,
                                        dtype=jnp.float32) * 10.0
                return logits, dict(caches, pool=pool)

            return (init_caches, embed_fn, step_fn, head_fn, None,
                    jax.jit(ragged_prefill, donate_argnums=(2,)),
                    fused_tick)
        return init_caches, embed_fn, step_fn, head_fn, None

    def _run_prefill(self, bundle, ids_np, chunk=None, caches=None, t0=0):
        init_caches = bundle[0]
        ids = np.asarray(ids_np)
        B, T = ids.shape
        if caches is None:
            caches = init_caches(B)
        L, h, hd = self.L, self.H, self.HD
        vals = jnp.asarray(ids, jnp.float32)[None, :, :, None, None]
        vals = jnp.broadcast_to(vals, (L, B, T, h, hd))
        caches = {"k": caches["k"].at[:, :, t0:t0 + T].set(vals),
                  "v": caches["v"].at[:, :, t0:t0 + T].set(vals)}
        nxt = (7 * ids[:, -1].astype(np.int64) + (t0 + T - 1) + 1) % self.V
        logits = jax.nn.one_hot(jnp.asarray(nxt), self.V,
                                dtype=jnp.float32) * 10.0
        return logits, caches
