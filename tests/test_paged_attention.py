"""Paged KV cache + ragged paged-attention decode (PAPERS.md "Ragged
Paged Attention"): the Pallas kernel must match a naive gather oracle in
interpret mode, the XLA fallback must be BITWISE identical to the dense
decode attention, the page allocator must balance its books across slot
churn and prefix sharing, and ``ContinuousBatchingServer(
cache_backend="paged")`` must emit bit-identical tokens to the dense
backend (greedy and seeded sampling, mixed lengths, slot refill,
prefix-cache hits)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.kv_cache import OutOfPages, PagedKVCache
from paddle_tpu.ops.pallas import paged_attention as pa


def _rand(*shape, seed=0, scale=0.5):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)


def _model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _solo(model, ids, n_new, **kw):
    out = model.generate(pt.to_tensor(ids[None]), max_new_tokens=n_new,
                         max_cache_len=64, **kw).numpy()[0]
    return out[len(ids):]


# ------------------------------------------------------------- kernel


class TestPagedAttentionKernel:
    @pytest.mark.parametrize("kvh,nh", [(2, 2), (2, 4)])  # MHA and GQA
    def test_kernel_matches_gather_oracle(self, kvh, nh):
        S, hd, P, pg, maxp = 4, 32, 12, 8, 4
        q = _rand(S, nh, hd, seed=1)
        kp = _rand(P, pg, kvh, hd, seed=2)
        vp = _rand(P, pg, kvh, hd, seed=3)
        rng = np.random.RandomState(4)
        bt = jnp.asarray(np.stack([
            rng.choice(np.arange(1, P), maxp, replace=False)
            for _ in range(S)]).astype(np.int32))
        # ragged: page-boundary, mid-page, single-token, full lengths
        lengths = jnp.asarray(np.array([pg, 13, 1, maxp * pg], np.int32))
        out = pa._paged_attention_pallas(q, kp, vp, bt, lengths,
                                         1.0 / np.sqrt(hd),
                                         interpret=True)
        ref = pa._ref_paged_attention(q, kp, vp, bt, lengths,
                                      1.0 / np.sqrt(hd))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_kernel_ignores_stale_tail_pages(self):
        """Block-table entries past a slot's length point at the null
        page (or stale pages); their contents must not leak into the
        output."""
        S, nh, kvh, hd, P, pg, maxp = 2, 2, 2, 32, 8, 8, 3
        q = _rand(S, nh, hd, seed=5)
        kp = _rand(P, pg, kvh, hd, seed=6)
        vp = _rand(P, pg, kvh, hd, seed=7)
        bt = jnp.asarray(np.array([[1, 0, 0], [2, 3, 0]], np.int32))
        lengths = jnp.asarray(np.array([5, 11], np.int32))
        out1 = pa._paged_attention_pallas(q, kp, vp, bt, lengths, 0.2,
                                          interpret=True)
        # poison everything the lengths say is invalid
        kp2 = kp.at[0].set(1e3).at[4:].set(-1e3)
        vp2 = vp.at[0].set(1e3).at[4:].set(-1e3)
        kp2 = kp2.at[1, 5:].set(77.0)        # slot 0 rows past length 5
        vp2 = vp2.at[1, 5:].set(77.0)
        kp2 = kp2.at[3, 3:].set(-77.0)       # slot 1 rows past 11 = 8+3
        vp2 = vp2.at[3, 3:].set(-77.0)
        out2 = pa._paged_attention_pallas(q, kp2, vp2, bt, lengths, 0.2,
                                          interpret=True)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    def test_ref_path_bitwise_matches_dense_attend(self):
        """The gather fallback mirrors generation._cached_attend op for
        op — paging a dense cache must not change a single bit."""
        from paddle_tpu.models.generation import _cached_attend
        B, nh, kvh, hd, T, pg = 3, 4, 2, 16, 32, 8
        maxp = T // pg
        q = _rand(B, 1, nh, hd, seed=8)
        kc = _rand(B, T, kvh, hd, seed=9)
        vc = _rand(B, T, kvh, hd, seed=10)
        t = jnp.asarray(np.array([4, 17, 31], np.int32))   # lengths-1
        kk = jnp.repeat(kc, nh // kvh, axis=2)
        vv = jnp.repeat(vc, nh // kvh, axis=2)
        want = _cached_attend(q, kk, vv, t, 1, 0.25)       # [B,1,nh,hd]

        # page the dense cache: slot b gets pages [1+b*maxp, ...)
        P = 1 + B * maxp
        kp = jnp.zeros((P, pg, kvh, hd), jnp.float32)
        vp = jnp.zeros((P, pg, kvh, hd), jnp.float32)
        bt = np.zeros((B, maxp), np.int32)
        for b in range(B):
            ids = 1 + b * maxp + np.arange(maxp)
            bt[b] = ids
            kp = kp.at[ids].set(kc[b].reshape(maxp, pg, kvh, hd))
            vp = vp.at[ids].set(vc[b].reshape(maxp, pg, kvh, hd))
        got = pa._ref_paged_attention(q[:, 0], kp, vp, jnp.asarray(bt),
                                      t + 1, 0.25)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want[:, 0]))


@pytest.mark.slow
class TestPagedAttentionOnChip:
    """Compiled (non-interpret) kernel path — needs a real TPU backend;
    CPU tier-1 covers the same math through interpret mode above."""

    def test_compiled_kernel_matches_oracle(self):
        if not pa.available():
            pytest.skip("needs a TPU backend")
        S, nh, kvh, hd, P, pg, maxp = 8, 8, 2, 128, 64, 32, 8
        q = _rand(S, nh, hd, seed=1)
        kp = _rand(P, pg, kvh, hd, seed=2)
        vp = _rand(P, pg, kvh, hd, seed=3)
        rng = np.random.RandomState(4)
        bt = jnp.asarray(np.stack([
            rng.choice(np.arange(1, P), maxp, replace=False)
            for _ in range(S)]).astype(np.int32))
        lengths = jnp.asarray(
            rng.randint(1, maxp * pg + 1, (S,)).astype(np.int32))
        out = pa.paged_attention(q, kp, vp, bt, lengths)
        ref = pa._ref_paged_attention(q, kp, vp, bt, lengths,
                                      1.0 / np.sqrt(hd))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)


# ------------------------------------------------------------ allocator


class TestPagedKVCache:
    def test_alloc_free_lifecycle_and_null_page(self):
        kv = PagedKVCache(num_pages=9, page_size=4, max_slots=2,
                          pages_per_slot=4)
        assert kv.free_pages() == 8            # page 0 reserved
        own = kv.admit_slot(0, 10)             # ceil(10/4) = 3 pages
        assert len(own) == 3 and 0 not in own
        assert kv.coverage(0) == 12
        assert (kv.block_table[0, :3] == own).all()
        assert (kv.block_table[0, 3:] == 0).all()
        assert kv.used_pages() == 3
        kv.free_slot(0)
        assert kv.used_pages() == 0 and kv.free_pages() == 8
        assert (kv.block_table[0] == 0).all()

    def test_out_of_pages_and_oversubscription(self):
        kv = PagedKVCache(num_pages=5, page_size=4, max_slots=2,
                          pages_per_slot=4)
        kv.admit_slot(0, 12)                   # 3 of 4 pages
        with pytest.raises(OutOfPages):
            kv.admit_slot(1, 8)                # needs 2, only 1 free
        kv.free_slot(0)
        kv.admit_slot(1, 8)                    # now fits
        with pytest.raises(ValueError):
            kv.admit_slot(0, 17)               # > pages_per_slot

    def test_shared_prefix_pages_refcounted(self):
        kv = PagedKVCache(num_pages=12, page_size=4, max_slots=3,
                          pages_per_slot=4)
        shared = kv.alloc(2)                   # registry holds one ref
        base_used = kv.used_pages()
        kv.admit_slot(0, 12, shared_pages=shared)
        kv.admit_slot(1, 10, shared_pages=shared)
        # 2 shared (stored once) + 1 own each
        assert kv.used_pages() == base_used + 2
        assert list(kv.block_table[0, :2]) == shared
        assert list(kv.block_table[1, :2]) == shared
        kv.free_slot(0)
        kv.free_slot(1)
        # registry ref keeps the shared pages alive
        assert kv.used_pages() == base_used == 2

    def test_hbm_accounting(self):
        paged = PagedKVCache.paged_hbm_bytes(num_pages=65, page_size=16,
                                             layers=2, kv_heads=2,
                                             head_dim=32, itemsize=4)
        dense = PagedKVCache.dense_hbm_bytes(max_slots=8,
                                             max_cache_len=1024,
                                             layers=2, kv_heads=2,
                                             head_dim=32, itemsize=4)
        assert paged * 7 < dense               # ~8x smaller pool


# -------------------------------------------------------------- server


class TestPagedServer:
    def _both(self, model, prompts, n_new, page_size=8, num_pages=None,
              **kw):
        """Run the same workload through dense and paged servers and
        assert bit-identical per-request tokens."""
        dense = ContinuousBatchingServer(model, max_slots=2,
                                         max_cache_len=64, **kw)
        paged = ContinuousBatchingServer(model, max_slots=2,
                                         max_cache_len=64,
                                         cache_backend="paged",
                                         page_size=page_size,
                                         num_pages=num_pages, **kw)
        seeds = list(range(100, 100 + len(prompts)))
        rd = [dense.submit(p, max_new_tokens=n_new, seed=s)
              for p, s in zip(prompts, seeds)]
        rp = [paged.submit(p, max_new_tokens=n_new, seed=s)
              for p, s in zip(prompts, seeds)]
        od, op = dense.run(), paged.run()
        for a, b in zip(rd, rp):
            np.testing.assert_array_equal(od[a], op[b])
        return paged

    def test_greedy_parity_with_slot_refill(self):
        model = _model()
        rng = np.random.default_rng(0)
        # 5 requests through 2 slots: refill mid-run, mixed lengths
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (3, 9, 5, 12, 4)]
        srv = self._both(model, prompts, 6)
        # every page is either back on the free list or held by the
        # auto prefix cache (the 9- and 12-token prompts each donated
        # one full page); none is leaked to a dead slot
        free, live, pinned, cached = srv.pool_balance()
        assert (live, pinned, cached) == (0, 0, 2)

    def test_sampled_parity_seeded(self):
        model = _model()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 6, 5)]
        self._both(model, prompts, 7, do_sample=True, temperature=1.3,
                   top_k=9)

    def test_tick_block_parity(self):
        model = _model()
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 7, 5)]
        self._both(model, prompts, 7, tick_block=4)

    def test_small_pool_defers_admission_with_parity(self):
        """A pool too small for every request at once: admission waits
        for pages without changing any tokens."""
        model = _model()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 6, 5, 3)]
        # room for ~1.5 slots' worth of pages (64-token budget = 8 pages)
        srv = self._both(model, prompts, 6, num_pages=13)
        assert srv._kv.used_pages() == 0

    def test_admission_reserves_full_extent_no_midrun_oom(self):
        """Admission reserves prompt + budget pages, so a pool with room
        for the prompts of two slots but not their decode growth admits
        ONE at a time instead of crashing OutOfPages mid-decode."""
        model = _model()
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 256, (8,)).astype(np.int32)
                   for _ in range(2)]
        # extent 8 + 48 = 56 tokens = 7 pages per request; 12 usable
        # pages hold one reservation, not two
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8, num_pages=13)
        rids = [srv.submit(p, max_new_tokens=48) for p in prompts]
        outs = srv.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], _solo(model, p, 48))
        free, live, pinned, cached = srv.pool_balance()
        assert (live, cached) == (0, 2)        # one donated page each

    def test_tick_block_tight_pool_no_midstep_alloc(self):
        """tick_block > 1 on a pool with zero spare pages: block steps
        past a slot's budget go to the null page and must not try to
        allocate coverage (would OutOfPages on a legally sized pool)."""
        model = _model()
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 256, (8,)).astype(np.int32)
                   for _ in range(2)]
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8, num_pages=5,
                                       tick_block=16)
        rids = [srv.submit(p, max_new_tokens=2) for p in prompts]
        outs = srv.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], _solo(model, p, 2))
        free, live, pinned, cached = srv.pool_balance()
        assert (live, cached) == (0, 2)        # one donated page each

    def test_register_prefix_refuses_to_strand_queued_request(self):
        """Pinning prefix pages after a submit must not silently starve
        the queue: a registration that makes a queued request forever
        unadmittable is rejected (and rolled back)."""
        model = _model()
        rng = np.random.default_rng(7)
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8, num_pages=9)
        # queued head needs all 8 usable pages
        srv.submit(rng.integers(0, 256, (8,)).astype(np.int32),
                   max_new_tokens=56)
        prefix = rng.integers(0, 256, (16,)).astype(np.int32)
        with pytest.raises(ValueError, match="strand"):
            srv.register_prefix(prefix)
        assert srv._kv.used_pages() == 0       # rollback complete
        assert srv._prefixes == []
        srv.run()                              # queued request unharmed

    def test_prefix_pages_shared_once_with_parity(self):
        model = _model()
        rng = np.random.default_rng(4)
        prefix = rng.integers(0, 256, (10,)).astype(np.int32)
        tails = [rng.integers(0, 256, (n,)).astype(np.int32)
                 for n in (3, 5)]
        prompts = [np.concatenate([prefix, t]) for t in tails]

        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8)
        srv.register_prefix(prefix)
        # the 10-token prefix pins exactly one full 8-token page;
        # re-registering (client retry) is an idempotent no-op
        assert srv._kv.used_pages() == 1
        assert srv.register_prefix(prefix) == 10
        assert srv._kv.used_pages() == 1 and len(srv._prefixes) == 1
        rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
        outs = srv.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], _solo(model, p, 6))
        # RAGGED prefill (the paged default, ISSUE 6): registered hits
        # reuse the prefix's page-aligned run through the radix tree —
        # the 10-token prefix pins one full 8-token page, so each
        # request reuses 8 tokens and re-prefills its 2-token sub-page
        # tail with the remainder (recomputation is deterministic;
        # tokens stay bit-identical, asserted above). The PR-5 dense
        # path (prefill_mode="dense") seeded the exact 10 rows instead:
        # 20 hit tokens / 18 prefill — the page-granular accounting is
        # the deliberate ISSUE-6 contract for ragged mode.
        assert srv.stats["prefix_hit_tokens"] == 2 * 8
        assert srv.stats["prefill_tokens"] == 10 + (2 + 3) + (2 + 5)
        assert srv._kv.used_pages() == 1

    def test_eos_frees_pages_early(self):
        model = _model()
        rng = np.random.default_rng(2)
        p = rng.integers(0, 256, (4,)).astype(np.int32)
        solo = _solo(model, p, 8)
        eos = int(solo[2])
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8, eos_token_id=eos)
        rid = srv.submit(p, max_new_tokens=8)
        out = srv.run()[rid]
        np.testing.assert_array_equal(out, solo[:len(out)])
        assert srv._kv.used_pages() == 0

    def test_cancel_mid_flight_frees_pages(self):
        model = _model()
        rng = np.random.default_rng(11)
        a = rng.integers(0, 256, (4,)).astype(np.int32)
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8)
        ra = srv.submit(a, max_new_tokens=10)
        for _ in range(3):
            srv.step()
        assert srv._kv.used_pages() > 0
        assert srv.cancel(ra) is True
        srv.run()
        assert srv._kv.used_pages() == 0

    @pytest.mark.slow
    def test_gpt_and_mixtral_paged_parity(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        from paddle_tpu.models.mixtral import (MixtralForCausalLM,
                                               mixtral_tiny)
        rng = np.random.default_rng(8)
        pt.seed(22)
        g = GPTForCausalLM(gpt2_tiny())
        g.eval()
        p = rng.integers(0, g.cfg.vocab_size, (4,)).astype(np.int32)
        srv = ContinuousBatchingServer(g, max_slots=2, max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=16)
        rid = srv.submit(p, max_new_tokens=5)
        np.testing.assert_array_equal(srv.run()[rid], _solo(g, p, 5))

        pt.seed(24)
        moe = MixtralForCausalLM(mixtral_tiny())
        moe.eval()
        p = rng.integers(0, 256, (5,)).astype(np.int32)
        srv = ContinuousBatchingServer(moe, max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8)
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid], _solo(moe, p, 4))

    def test_config_guards(self):
        model = _model()
        with pytest.raises(ValueError, match="divide max_cache_len"):
            ContinuousBatchingServer(model, max_cache_len=64,
                                     cache_backend="paged", page_size=7)
        with pytest.raises(ValueError, match="cache_backend"):
            ContinuousBatchingServer(model, cache_backend="ragged")
        with pytest.raises(NotImplementedError):
            ContinuousBatchingServer(model, max_cache_len=64,
                                     cache_backend="paged", page_size=8,
                                     cache_dtype="int8")
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64,
                                       cache_backend="paged",
                                       page_size=8, num_pages=3)
        with pytest.raises(ValueError, match="grow num_pages"):
            srv.submit(np.zeros((20,), np.int32), max_new_tokens=4)
