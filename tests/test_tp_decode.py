"""Tensor-parallel decode (generate(mesh=...)): weights shard over the
mesh's mp axis (column/row-parallel + expert-parallel), GSPMD inserts
the collectives, and tokens must match the single-device decode."""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import paddle_tpu as pt


def _mesh(n=4):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]), ("mp",))


class TestTPDecode:
    def test_llama_tp_matches_single_device(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(71)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(14)
        ids = rng.integers(0, 256, (2, 5)).astype(np.int32)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=6,
                              max_cache_len=64)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=6,
                             max_cache_len=64, mesh=_mesh(4))
        np.testing.assert_array_equal(got.numpy(), want.numpy())

    def test_gpt_tp_matches_single_device(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(72)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        rng = np.random.default_rng(15)
        ids = rng.integers(0, model.cfg.vocab_size, (1, 4)).astype(
            np.int32)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                              max_cache_len=32)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                             max_cache_len=32, mesh=_mesh(4))
        np.testing.assert_array_equal(got.numpy(), want.numpy())

    def test_mixtral_expert_parallel_decode(self):
        """mixtral_tiny has 4 experts: a 4-way mesh shards one expert
        bank per device (expert-parallel serving)."""
        from paddle_tpu.models.mixtral import (MixtralForCausalLM,
                                               mixtral_tiny)
        pt.seed(73)
        model = MixtralForCausalLM(mixtral_tiny())
        model.eval()
        rng = np.random.default_rng(16)
        ids = rng.integers(0, 256, (1, 4)).astype(np.int32)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                              max_cache_len=64)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                             max_cache_len=64, mesh=_mesh(4))
        np.testing.assert_array_equal(got.numpy(), want.numpy())

    def test_tp_with_int8_weights(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(74)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        ids = np.arange(8, dtype=np.int32).reshape(2, 4)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                              max_cache_len=32, weight_dtype="int8")
        got = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             max_cache_len=32, weight_dtype="int8",
                             mesh=_mesh(4))
        np.testing.assert_array_equal(got.numpy(), want.numpy())

    def test_server_over_mesh(self):
        """Continuous batching with TP-sharded weights: same tokens."""
        from paddle_tpu.inference import ContinuousBatchingServer
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(76)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(18)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 6)]
        want = {}
        for i, p in enumerate(prompts):
            want[i] = model.generate(pt.to_tensor(p[None]),
                                     max_new_tokens=5,
                                     max_cache_len=64).numpy()[0, len(p):]
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64, mesh=_mesh(4))
        rids = [srv.submit(p, max_new_tokens=5) for p in prompts]
        outs = srv.run()
        for i, rid in enumerate(rids):
            np.testing.assert_array_equal(outs[rid], want[i])

    def test_indivisible_dims_fall_back_to_replicated(self):
        """llama_tiny kv heads (2) aren't divisible by 8; an 8-way mesh
        must still produce correct tokens (indivisible weights stay
        replicated)."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(75)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        ids = np.arange(6, dtype=np.int32).reshape(1, 6)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                              max_cache_len=32)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             max_cache_len=32, mesh=_mesh(8))
        np.testing.assert_array_equal(got.numpy(), want.numpy())
