"""Serving reliability layer through ContinuousBatchingServer and
BatchScheduler: deadlines, load shedding, supervised serve loop with
retry/backoff + circuit breaker, health states + /healthz, graceful
drain, and the satellite regressions (cancel notify, fire-all
callbacks, scheduler close with a wedged runner).

Runs on the StubModel double (tests/_serving_stub.py): no transformer
compiles, closed-form expected tokens, and FakeClock-driven deadlines —
fast enough for tier-1."""
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.serving import BatchScheduler, serve_metrics
from paddle_tpu.reliability import (CallbackError, CircuitBreaker,
                                    CircuitOpenError, DeadlineExceeded,
                                    FaultInjector, QueueFullError,
                                    RequestCancelled, RetryPolicy,
                                    SchedulerClosed, ServerClosed, faults)
from paddle_tpu.telemetry import FakeClock


def _prompt(*toks):
    return np.asarray(toks, np.int32)


def _srv(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 64)
    return ContinuousBatchingServer(StubModel(), **kw)


def _fast_retry():
    return RetryPolicy(base_delay_s=0.0, jitter=0.0)


def _until_queue_drains(srv, timeout=10.0):
    """Block until the serve thread has admitted everything queued —
    the deterministic way to build "slot busy, queue empty" fixtures."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with srv._lock:
            if not srv._queue:
                return
        time.sleep(0.005)
    raise AssertionError("queue never drained into slots")


# ---------------------------------------------------------- deadlines

class TestDeadlines:
    def test_expired_in_queue_fails_before_prefill(self):
        fc = FakeClock()
        srv = _srv(max_slots=1, clock=fc)
        ra = srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
        rb = srv.submit(_prompt(4, 5), max_new_tokens=4, deadline_s=5.0)
        fc.advance(10.0)                 # rb expires while still queued
        outs = srv.run()
        np.testing.assert_array_equal(outs[ra],
                                      stub_tokens(_prompt(1, 2, 3), 4))
        assert rb not in outs
        assert isinstance(srv.failures[rb], DeadlineExceeded)
        # the expired request never cost a prefill (only ra's 3 tokens)
        assert srv.stats["prefill_tokens"] == 3

    def test_mid_decode_expiry_records_partial(self):
        fc = FakeClock()
        srv = _srv(max_slots=1, clock=fc)
        p = _prompt(2, 7)
        rid = srv.submit(p, max_new_tokens=10, deadline_s=5.0)
        srv.step()                        # admit + 1 decode: 2 tokens
        srv.step()                        # 3 tokens
        fc.advance(6.0)
        srv.step()                        # expiry sweep cancels the slot
        outs = srv.run()
        np.testing.assert_array_equal(outs[rid], stub_tokens(p, 10)[:3])

    def test_submit_with_spent_deadline_rejected(self):
        srv = _srv()
        with pytest.raises(DeadlineExceeded):
            srv.submit(_prompt(1), max_new_tokens=2, deadline_s=0.0)

    def test_paged_expiry_frees_pages(self):
        fc = FakeClock()
        srv = _srv(max_slots=2, cache_backend="paged", page_size=8,
                   clock=fc)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=8,
                         deadline_s=1.0)
        srv.step()
        assert srv.pool_balance()[1] > 0          # pages live
        fc.advance(2.0)
        srv.step()
        srv.run()
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and pinned == 0
        assert rid is not None


# ----------------------------------------------------- load shedding

class TestLoadShedding:
    def test_reject_policy_raises_queue_full(self):
        srv = _srv(max_slots=1, max_queue=2)
        rids = [srv.submit(_prompt(i + 1), max_new_tokens=2)
                for i in range(2)]        # both queued (no step yet)
        with pytest.raises(QueueFullError, match="resubmit"):
            srv.submit(_prompt(9), max_new_tokens=2)
        outs = srv.run()                  # accepted requests unharmed
        assert set(outs) == set(rids)

    def test_evict_oldest_fails_oldest_accepts_new(self):
        srv = _srv(max_slots=1, max_queue=2, shed_policy="evict_oldest")
        old = srv.submit(_prompt(1), max_new_tokens=2)
        mid = srv.submit(_prompt(2), max_new_tokens=2)
        new = srv.submit(_prompt(3), max_new_tokens=2)   # evicts `old`
        outs = srv.run()
        assert old not in outs and {mid, new} <= set(outs)
        assert isinstance(srv.failures[old], QueueFullError)

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError, match="shed_policy"):
            _srv(shed_policy="drop_newest")

    def test_max_queue_zero_evict_policy_falls_back_to_reject(self):
        """Review regression: evict_oldest with nobody to evict
        (max_queue=0) must shed typed, not IndexError."""
        srv = _srv(max_queue=0, shed_policy="evict_oldest")
        with pytest.raises(QueueFullError):
            srv.submit(_prompt(1), max_new_tokens=2)


# ------------------------------------------------- supervised serving

class TestSupervisedLoop:
    def test_tick_fault_retries_in_flight_survive(self):
        """Acceptance: a tick exception no longer kills the serve
        thread — other slots finish and new submits are served without
        a restart."""
        fi = FaultInjector().on(faults.DECODE_TICK, schedule=[1])
        srv = _srv(retry_policy=_fast_retry(), fault_injector=fi,
                   telemetry=True).start()
        a, b = _prompt(1, 2, 3), _prompt(4, 5)
        ra = srv.submit(a, max_new_tokens=6)
        rb = srv.submit(b, max_new_tokens=6)
        np.testing.assert_array_equal(srv.wait(ra, timeout=60),
                                      stub_tokens(a, 6))
        np.testing.assert_array_equal(srv.wait(rb, timeout=60),
                                      stub_tokens(b, 6))
        assert fi.fired(faults.DECODE_TICK) == 1    # the fault DID fire
        # new submit on the same (never restarted) thread
        c = _prompt(7, 8)
        rc = srv.submit(c, max_new_tokens=3)
        np.testing.assert_array_equal(srv.wait(rc, timeout=60),
                                      stub_tokens(c, 3))
        assert srv.health == "healthy"
        m = srv.telemetry.registry.get("server_tick_retries_total")
        assert m.value == 1.0
        srv.stop()

    def test_injected_prefill_fault_fails_one_request_only(self):
        fi = FaultInjector().on(faults.PREFILL, schedule=[0])
        srv = _srv(max_slots=1, retry_policy=_fast_retry(),
                   fault_injector=fi).start()
        a, b = _prompt(1, 2), _prompt(3, 4)
        ra = srv.submit(a, max_new_tokens=4)   # first admission dies
        rb = srv.submit(b, max_new_tokens=4)
        with pytest.raises(Exception, match="injected fault"):
            srv.wait(ra, timeout=60)
        np.testing.assert_array_equal(srv.wait(rb, timeout=60),
                                      stub_tokens(b, 4))
        srv.stop()

    def test_breaker_opens_unblocks_waiters_then_recovers(self):
        fcb = FakeClock()
        fi = FaultInjector().on(faults.DECODE_TICK,
                                schedule=range(0, 1000))
        srv = _srv(retry_policy=_fast_retry(),
                   breaker=CircuitBreaker(failure_threshold=3,
                                          reset_after_s=10.0, clock=fcb),
                   fault_injector=fi, telemetry=True).start()
        rid = srv.submit(_prompt(1, 2), max_new_tokens=4)
        with pytest.raises(CircuitOpenError, match="circuit breaker"):
            srv.wait(rid, timeout=60)
        assert srv.health == "degraded"
        # heal the engine, let the cooldown elapse -> half-open probe
        fi.disarm()
        fcb.advance(11.0)
        p = _prompt(5, 6)
        rid2 = srv.submit(p, max_new_tokens=4)   # degraded still accepts
        np.testing.assert_array_equal(srv.wait(rid2, timeout=60),
                                      stub_tokens(p, 4))
        assert srv.health == "healthy"           # probe closed the loop
        reg = srv.telemetry.registry
        assert reg.get("server_breaker_open_total").value == 1.0
        assert reg.get("server_health").value == 0.0
        srv.stop()

    def test_idle_degraded_server_recovers_without_traffic(self):
        """Review regression: after a breaker trip empties the server,
        the cooldown must still close the breaker and clear `degraded`
        — an idle server must not alert forever."""
        fcb = FakeClock()
        fi = FaultInjector().on(faults.DECODE_TICK, schedule=range(3))
        srv = _srv(retry_policy=_fast_retry(),
                   breaker=CircuitBreaker(failure_threshold=3,
                                          reset_after_s=5.0, clock=fcb),
                   fault_injector=fi).start()
        rid = srv.submit(_prompt(1), max_new_tokens=4)
        with pytest.raises(CircuitOpenError):
            srv.wait(rid, timeout=60)
        assert srv.health == "degraded"
        fcb.advance(6.0)                  # cooldown elapses; NO traffic
        deadline = time.monotonic() + 10
        while srv.health != "healthy" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.health == "healthy"
        srv.stop()

    def test_deadline_enforced_during_breaker_cooldown(self):
        """Review regression: a queued request's deadline must fire
        even while the open breaker gates ticks."""
        fcb = FakeClock()                  # never advanced: cooldown
        fi = FaultInjector().on(faults.DECODE_TICK, schedule=range(3))
        srv = _srv(retry_policy=_fast_retry(),
                   breaker=CircuitBreaker(failure_threshold=3,
                                          reset_after_s=1e9, clock=fcb),
                   fault_injector=fi).start()
        rid = srv.submit(_prompt(1), max_new_tokens=4)
        with pytest.raises(CircuitOpenError):
            srv.wait(rid, timeout=60)      # breaker now open, stays open
        rid2 = srv.submit(_prompt(2), max_new_tokens=4, deadline_s=0.2)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            srv.wait(rid2, timeout=30)
        assert time.monotonic() - t0 < 10   # not the wait() timeout
        srv.stop()

    def test_final_chunk_callback_error_no_phantom_failure(self):
        """Review regression: budget=1 finishes at admission, so the
        poisoned callback fires AFTER harvest — the recorded result must
        stand and no phantom `failures` entry may accumulate."""
        srv = _srv(max_slots=1).start()
        rid = srv.submit(_prompt(5), max_new_tokens=1,
                         on_token=lambda r, t: 1 / 0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with srv._lock:
                if rid in srv._results:
                    break
            time.sleep(0.005)
        time.sleep(0.1)          # let the callback-error handler run
        assert rid not in srv.failures
        np.testing.assert_array_equal(srv.wait(rid, timeout=10),
                                      stub_tokens(_prompt(5), 1))
        srv.stop()

    def test_breaker_open_drops_stale_stream_chunks(self):
        """Review regression: chunks deferred by the tick that tripped
        the breaker must not fire after recovery — their requests
        already failed with CircuitOpenError."""
        fcb = FakeClock()
        fi = FaultInjector().on(faults.DECODE_TICK, schedule=[0, 1, 2])
        seen = []
        srv = _srv(retry_policy=_fast_retry(),
                   breaker=CircuitBreaker(failure_threshold=3,
                                          reset_after_s=5.0, clock=fcb),
                   fault_injector=fi).start()
        rid = srv.submit(_prompt(1, 2), max_new_tokens=4,
                         on_token=lambda r, t: seen.append(r))
        with pytest.raises(CircuitOpenError):
            srv.wait(rid, timeout=60)
        fcb.advance(6.0)                       # cooldown -> probe OK
        p = _prompt(3, 4)
        rid2 = srv.submit(p, max_new_tokens=3,
                          on_token=lambda r, t: seen.append(r))
        np.testing.assert_array_equal(srv.wait(rid2, timeout=60),
                                      stub_tokens(p, 3))
        assert rid not in seen, "stale chunk for a failed request fired"
        assert rid2 in seen
        srv.stop()

    def test_wait_raises_typed_errors_directly(self):
        srv = _srv(max_slots=1, max_queue=1, max_cache_len=8192,
                   shed_policy="evict_oldest").start()
        # wedge the slot with a long request so the queue backs up
        long_rid = srv.submit(_prompt(1), max_new_tokens=5000,
                              deadline_s=None)
        _until_queue_drains(srv)
        old = srv.submit(_prompt(2), max_new_tokens=2)
        srv.submit(_prompt(3), max_new_tokens=2)       # evicts `old`
        with pytest.raises(QueueFullError):
            srv.wait(old, timeout=10)
        srv.cancel(long_rid)
        srv.stop()


# ------------------------------------------------- health and drain

class TestHealthAndDrain:
    def test_drain_finishes_queue_then_dies(self):
        srv = _srv(max_slots=1).start()
        a, b = _prompt(1, 2), _prompt(3)
        ra = srv.submit(a, max_new_tokens=5)
        rb = srv.submit(b, max_new_tokens=5)
        srv.stop(drain=True)
        assert srv.health == "dead"
        with pytest.raises(ServerClosed):
            srv.submit(_prompt(9), max_new_tokens=2)
        # results were flushed, waiters can still collect
        np.testing.assert_array_equal(srv.wait(ra, timeout=5),
                                      stub_tokens(a, 5))
        np.testing.assert_array_equal(srv.wait(rb, timeout=5),
                                      stub_tokens(b, 5))

    def test_hard_stop_fails_queued_flushes_partials(self):
        srv = _srv(max_slots=1, max_cache_len=8192).start()
        ra = srv.submit(_prompt(1), max_new_tokens=5000)  # never finishes
        _until_queue_drains(srv)                          # ra holds the slot
        rb = srv.submit(_prompt(2), max_new_tokens=2)     # stuck queued
        srv.stop()
        out = srv.wait(ra, timeout=5)                     # partial flush
        assert 1 <= len(out) < 5000
        np.testing.assert_array_equal(
            out, stub_tokens(_prompt(1), 5000)[:len(out)])
        with pytest.raises(ServerClosed):
            srv.wait(rb, timeout=5)

    def test_submit_racing_drain_rejected_typed_healthz_503(self):
        """ISSUE 7 satellite: a submit() racing stop(drain=True) must
        be rejected TYPED (ServerClosed — never silently dropped, never
        admitted into a dying server), and /healthz must answer 503 for
        the whole drain window (draining) and after it (dead). The
        in-flight request pins the drain open via a gated on_token
        callback, so the window is deterministic, not a sleep race."""
        srv = _srv(max_slots=1, telemetry=True).start()
        ms = serve_metrics(srv)
        entered, release = threading.Event(), threading.Event()

        def gate(rid, toks):
            entered.set()
            assert release.wait(timeout=30)

        p = _prompt(1, 2, 3)
        rid = srv.submit(p, max_new_tokens=8, on_token=gate)
        assert entered.wait(timeout=30)     # mid-decode, stream gated
        t = threading.Thread(target=lambda: srv.stop(drain=True,
                                                     timeout=60))
        t.start()
        try:
            deadline = time.monotonic() + 30
            while srv.health != "draining":
                assert time.monotonic() < deadline, "never saw draining"
                time.sleep(0.002)
            # the drain window is OPEN (in-flight request gated):
            # admission must refuse typed...
            with pytest.raises(ServerClosed):
                srv.submit(_prompt(9), max_new_tokens=2)
            # ...and the readiness probe must already say 503
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(ms.url + "/healthz")
            assert ei.value.code == 503
            assert b'"draining"' in ei.value.read()
        finally:
            release.set()
            t.join(timeout=60)
        assert not t.is_alive()
        # drained, not dropped: the in-flight request completed in full
        np.testing.assert_array_equal(srv.wait(rid, timeout=5),
                                      stub_tokens(p, 8))
        # after the drain the server is dead — still 503, same verdict
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(ms.url + "/healthz")
        assert ei.value.code == 503
        ms.close()

    def test_queued_requests_complete_during_drain_race(self):
        """Admissions QUEUED before the drain began are not shed by it:
        stop(drain=True) completes them; only post-drain submits see
        ServerClosed."""
        srv = _srv(max_slots=1).start()
        entered, release = threading.Event(), threading.Event()

        def gate(rid, toks):
            entered.set()
            assert release.wait(timeout=30)

        a, b = _prompt(1, 2), _prompt(3, 4)
        ra = srv.submit(a, max_new_tokens=4, on_token=gate)
        assert entered.wait(timeout=30)
        rb = srv.submit(b, max_new_tokens=4)    # queued behind ra
        stopper = threading.Thread(
            target=lambda: srv.stop(drain=True, timeout=60))
        stopper.start()
        deadline = time.monotonic() + 30
        while srv.health != "draining":
            assert time.monotonic() < deadline
            time.sleep(0.002)
        with pytest.raises(ServerClosed):
            srv.submit(_prompt(5), max_new_tokens=1)
        release.set()
        stopper.join(timeout=60)
        assert not stopper.is_alive()
        np.testing.assert_array_equal(srv.wait(ra, timeout=5),
                                      stub_tokens(a, 4))
        np.testing.assert_array_equal(srv.wait(rb, timeout=5),
                                      stub_tokens(b, 4))
        assert not srv.failures

    def test_restart_after_stop_resets_health(self):
        srv = _srv().start()
        srv.stop()
        assert srv.health == "dead"
        srv.start()
        assert srv.health == "healthy"
        p = _prompt(4, 4)
        rid = srv.submit(p, max_new_tokens=3)
        np.testing.assert_array_equal(srv.wait(rid, timeout=60),
                                      stub_tokens(p, 3))
        srv.stop()

    def test_healthz_and_reliability_metrics_exposed(self):
        srv = _srv(telemetry=True, max_queue=1, max_slots=1,
                   max_cache_len=8192).start()
        ms = serve_metrics(srv)
        try:
            with urllib.request.urlopen(ms.url + "/healthz") as r:
                assert r.status == 200
                assert b'"healthy"' in r.read()
            # trip a shed so the counter is nonzero in the exposition
            srv.submit(_prompt(1), max_new_tokens=4000)
            _until_queue_drains(srv)       # it holds the single slot
            srv.submit(_prompt(2), max_new_tokens=2)
            with pytest.raises(QueueFullError):
                srv.submit(_prompt(3), max_new_tokens=2)
            with urllib.request.urlopen(ms.url + "/metrics") as r:
                text = r.read().decode()
            for name in ("server_shed_total", "server_deadline_expired_total",
                         "server_tick_retries_total", "server_health"):
                assert name in text, name
            assert 'server_shed_total{policy="reject"} 1' in text
            srv.stop()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(ms.url + "/healthz")
            assert ei.value.code == 503
            assert b'"dead"' in ei.value.read()
        finally:
            ms.close()


# ------------------------------------------- satellite regressions

class TestSatelliteRegressions:
    def test_cancel_notifies_waiter_immediately(self):
        """Satellite 1: cancel() must notify _done_cv — a blocked
        wait() returns the partial NOW, not at the next 1 s poll."""
        srv = _srv(max_slots=1, max_cache_len=8192).start()
        rid = srv.submit(_prompt(3), max_new_tokens=5000)
        got = {}

        def waiter():
            t0 = time.monotonic()
            got["out"] = srv.wait(rid, timeout=30)
            got["dt"] = time.monotonic() - t0

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.15)                    # waiter is parked in wait()
        assert srv.cancel(rid) is True
        th.join(timeout=10)
        assert "out" in got
        # well under the 1 s condition-poll fallback: the notify did it
        assert got["dt"] < 0.95
        np.testing.assert_array_equal(
            got["out"], stub_tokens(_prompt(3), 5000)[:len(got["out"])])
        srv.stop()

    def test_cancel_queued_raises_typed_error_in_wait(self):
        srv = _srv(max_slots=1, max_cache_len=8192).start()
        busy = srv.submit(_prompt(1), max_new_tokens=5000)
        _until_queue_drains(srv)
        rid = srv.submit(_prompt(2), max_new_tokens=2)
        assert srv.cancel(rid) is True
        with pytest.raises(RequestCancelled):
            srv.wait(rid, timeout=10)
        srv.cancel(busy)
        srv.stop()

    def test_fire_callbacks_fires_all_then_raises_first(self):
        """Satellite 2: one poisoned on_token must not eat the other
        requests' queued chunks — they fire, THEN the error surfaces."""
        good = []
        srv = _srv(max_slots=2, tick_block=2)
        # poisoned request admitted FIRST (slot 0, fires first)
        rb = srv.submit(_prompt(9, 9), max_new_tokens=6,
                        on_token=lambda r, t: 1 / 0)
        ra = srv.submit(_prompt(1, 2), max_new_tokens=6,
                        on_token=lambda r, t: good.append(t.copy()))
        with pytest.raises(CallbackError) as ei:
            srv.run()
        assert ei.value.rid == rb
        assert isinstance(ei.value.__cause__, ZeroDivisionError)
        assert good, "good request's chunk was dropped by the poisoned one"
        np.testing.assert_array_equal(
            np.concatenate(good)[:1], stub_tokens(_prompt(1, 2), 6)[:1])
        assert ra is not None

    def test_scheduler_close_fails_pending_on_wedged_runner(self):
        """Satellite 3: close() must not leave futures hanging when the
        runner wedges — they fail typed, and the timeout surfaces."""
        release = threading.Event()

        def runner(arrs):
            release.wait(30)
            return [arrs[0]]

        sched = BatchScheduler(runner, max_batch_size=1, max_delay_ms=1.0)
        f1 = sched.submit(np.ones((1, 2), np.float32))
        time.sleep(0.15)                   # worker is inside runner now
        f2 = sched.submit(np.ones((1, 2), np.float32))
        with pytest.raises(TimeoutError, match="did not exit"):
            sched.close(timeout=0.3)
        assert isinstance(f1.exception(timeout=5), SchedulerClosed)
        assert isinstance(f2.exception(timeout=5), SchedulerClosed)
        release.set()                      # unwedge; late result ignored

    def test_scheduler_queue_bound_and_deadline(self):
        release = threading.Event()

        def runner(arrs):
            release.wait(30)
            return [arrs[0]]

        sched = BatchScheduler(runner, max_batch_size=1, max_delay_ms=1.0,
                               max_queue=1)
        f1 = sched.submit(np.ones((1, 2), np.float32))
        time.sleep(0.15)                   # f1 in flight, queue empty
        f2 = sched.submit(np.ones((1, 2), np.float32))
        with pytest.raises(QueueFullError, match="max_queue"):
            sched.submit(np.ones((1, 2), np.float32))
        # a queued request whose deadline passes fails before launch
        f3 = None
        release.set()
        time.sleep(0.05)
        f3 = sched.submit(np.ones((1, 2), np.float32), deadline_s=0.0)
        assert isinstance(f3.exception(timeout=5), DeadlineExceeded)
        assert f1.result(timeout=5) is not None
        assert f2.result(timeout=5) is not None
        sched.close()

    def test_scheduler_submit_after_close_typed(self):
        sched = BatchScheduler(lambda s: [s[0]], max_batch_size=2)
        sched.close()
        with pytest.raises(SchedulerClosed, match="closed"):
            sched.submit(np.ones((1, 2), np.float32))
