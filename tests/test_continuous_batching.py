"""Continuous-batching server (inference/continuous_batching.py): results
for every request must equal a solo model.generate() run — slots are
row-wise independent, so batching and mid-flight admission cannot change
tokens."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer


def _model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _solo(model, ids, n_new, **kw):
    out = model.generate(pt.to_tensor(ids[None]), max_new_tokens=n_new,
                         max_cache_len=64, **kw).numpy()[0]
    return out[len(ids):]


class TestContinuousBatching:
    def test_more_requests_than_slots_match_solo(self):
        model = _model()
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (3, 5, 4)]
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64)
        rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
        outs = srv.run()
        assert set(outs) == set(rids)
        for rid, prompt in zip(rids, prompts):
            want = _solo(model, prompt, 6)
            np.testing.assert_array_equal(outs[rid], want)

    def test_mid_flight_admission_does_not_disturb(self):
        model = _model()
        rng = np.random.default_rng(1)
        a = rng.integers(0, 256, (4,)).astype(np.int32)
        b = rng.integers(0, 256, (6,)).astype(np.int32)
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64)
        ra = srv.submit(a, max_new_tokens=8)
        for _ in range(3):          # a is mid-decode when b arrives
            srv.step()
        rb = srv.submit(b, max_new_tokens=5)
        outs = srv.run()
        np.testing.assert_array_equal(outs[ra], _solo(model, a, 8))
        np.testing.assert_array_equal(outs[rb], _solo(model, b, 5))

    def test_eos_frees_slot_early(self):
        model = _model()
        rng = np.random.default_rng(2)
        p = rng.integers(0, 256, (4,)).astype(np.int32)
        solo = _solo(model, p, 8)
        eos = int(solo[2])          # third generated token acts as eos
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64,
                                       eos_token_id=eos)
        rid = srv.submit(p, max_new_tokens=8)
        out = srv.run()[rid]
        assert out[-1] == eos and len(out) <= 8
        np.testing.assert_array_equal(out, solo[:len(out)])

    def test_length_guard_and_batch_submit_rejected(self):
        model = _model()
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=16)
        with pytest.raises(ValueError, match="max_cache_len"):
            srv.submit(np.zeros((12,), np.int32), max_new_tokens=8)
        with pytest.raises(ValueError, match="one request"):
            srv.submit(np.zeros((2, 4), np.int32))
        # chunk-pad overflow must be rejected AT SUBMIT (not lost later
        # inside step(): code-review r5)
        srv2 = ContinuousBatchingServer(model, max_slots=1,
                                        max_cache_len=16,
                                        prefill_chunk=6)
        with pytest.raises(ValueError, match="pad rows"):
            srv2.submit(np.zeros((13,), np.int32), max_new_tokens=3)

    def test_tick_block_parity_greedy_and_sampled(self):
        """tick_block=4 (four decode steps per dispatch) changes neither
        greedy nor sampled tokens vs tick_block=1/solo."""
        model = _model()
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 6, 5)]
        for kw in (dict(), dict(do_sample=True, temperature=1.3,
                                top_k=9)):
            srv = ContinuousBatchingServer(model, max_slots=2,
                                           max_cache_len=64,
                                           tick_block=4, **kw)
            rids = [srv.submit(p, max_new_tokens=7, seed=200 + i)
                    for i, p in enumerate(prompts)]
            outs = srv.run()
            for i, (rid, p) in enumerate(zip(rids, prompts)):
                want = model.generate(
                    pt.to_tensor(p[None]), max_new_tokens=7,
                    seed=200 + i, max_cache_len=64,
                    **kw).numpy()[0, len(p):]
                np.testing.assert_array_equal(outs[rid], want)

    def test_tick_block_eos_mid_block(self):
        """A slot hitting eos inside a block stops there; trailing block
        tokens are discarded and the slot refills."""
        model = _model()
        rng = np.random.default_rng(7)
        p = rng.integers(0, 256, (4,)).astype(np.int32)
        solo = _solo(model, p, 8)
        # eos = a token whose FIRST occurrence is mid-sequence
        eos, cut = None, None
        for j in range(1, len(solo)):
            if solo[j] not in solo[:j]:
                eos, cut = int(solo[j]), j
        assert eos is not None, "degenerate sequence; change seed"
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64,
                                       eos_token_id=eos, tick_block=5)
        rid = srv.submit(p, max_new_tokens=8)
        rid2 = srv.submit(p, max_new_tokens=8)   # refills the same slot
        outs = srv.run()
        np.testing.assert_array_equal(outs[rid], solo[:cut + 1])
        np.testing.assert_array_equal(outs[rid2], solo[:cut + 1])

    def test_sampled_requests_match_solo_generate(self):
        """Per-request PRNG chains: submit(seed=s) draws exactly what a
        solo generate(do_sample=True, seed=s) draws, even with both
        slots mid-flight."""
        model = _model()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 6, 5)]
        kw = dict(do_sample=True, temperature=1.5, top_k=7)
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64, **kw)
        rids = [srv.submit(p, max_new_tokens=7, seed=100 + i)
                for i, p in enumerate(prompts)]
        outs = srv.run()
        for i, (rid, p) in enumerate(zip(rids, prompts)):
            want = model.generate(pt.to_tensor(p[None]), max_new_tokens=7,
                                  seed=100 + i, max_cache_len=64,
                                  **kw).numpy()[0, len(p):]
            np.testing.assert_array_equal(outs[rid], want)

    def test_prefix_cache_parity_and_savings(self):
        """Registered shared prefix: identical tokens, remainder-only
        prefill work."""
        model = _model()
        rng = np.random.default_rng(4)
        prefix = rng.integers(0, 256, (10,)).astype(np.int32)
        tails = [rng.integers(0, 256, (n,)).astype(np.int32)
                 for n in (3, 5)]
        prompts = [np.concatenate([prefix, t]) for t in tails]

        plain = ContinuousBatchingServer(model, max_slots=2,
                                         max_cache_len=64)
        rids = [plain.submit(p, max_new_tokens=6) for p in prompts]
        want = plain.run()

        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64)
        srv.register_prefix(prefix)
        rids2 = [srv.submit(p, max_new_tokens=6) for p in prompts]
        outs = srv.run()
        for ra, rb in zip(rids, rids2):
            np.testing.assert_array_equal(outs[rb], want[ra])
        # prefill work: 10 (register) + 3 + 5 vs 13 + 15
        assert srv.stats["prefill_tokens"] == 10 + 3 + 5
        assert srv.stats["prefix_hit_tokens"] == 20
        assert plain.stats["prefill_tokens"] == 13 + 15

    def test_prefix_exact_match_uses_stored_logits(self):
        """A prompt equal to the prefix itself prefills zero tokens."""
        model = _model()
        rng = np.random.default_rng(5)
        prefix = rng.integers(0, 256, (8,)).astype(np.int32)
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64)
        srv.register_prefix(prefix)
        base = srv.stats["prefill_tokens"]
        rid = srv.submit(prefix, max_new_tokens=5)
        out = srv.run()[rid]
        assert srv.stats["prefill_tokens"] == base   # no extra prefill
        want = _solo(model, prefix, 5)
        np.testing.assert_array_equal(out, want)

    def test_mixtral_and_int8_through_server(self):
        """The server is model-agnostic: MoE decode and weight-only int8
        both serve with solo-parity."""
        from paddle_tpu.models.mixtral import (MixtralForCausalLM,
                                               mixtral_tiny)
        pt.seed(24)
        moe = MixtralForCausalLM(mixtral_tiny())
        moe.eval()
        rng = np.random.default_rng(8)
        p = rng.integers(0, 256, (5,)).astype(np.int32)
        want = moe.generate(pt.to_tensor(p[None]), max_new_tokens=4,
                            max_cache_len=64).numpy()[0, 5:]
        srv = ContinuousBatchingServer(moe, max_slots=2, max_cache_len=64)
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid], want)

        lm = _model()
        want8 = lm.generate(pt.to_tensor(p[None]), max_new_tokens=4,
                            max_cache_len=64,
                            weight_dtype="int8").numpy()[0, 5:]
        srv8 = ContinuousBatchingServer(lm, max_slots=1, max_cache_len=64,
                                        weight_dtype="int8")
        rid = srv8.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv8.run()[rid], want8)

    def test_streaming_chunks_concatenate_to_result(self):
        model = _model()
        rng = np.random.default_rng(10)
        p = rng.integers(0, 256, (4,)).astype(np.int32)
        chunks = []
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64, tick_block=3)
        rid = srv.submit(p, max_new_tokens=7,
                         on_token=lambda r, t: chunks.append((r, t)))
        out = srv.run()[rid]
        assert all(r == rid for r, _ in chunks)
        np.testing.assert_array_equal(
            np.concatenate([t for _, t in chunks]), out)
        assert len(chunks) >= 3       # admission token + >=2 blocks

    def test_cancel_queued_and_mid_flight(self):
        model = _model()
        rng = np.random.default_rng(11)
        a = rng.integers(0, 256, (4,)).astype(np.int32)
        b = rng.integers(0, 256, (5,)).astype(np.int32)
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64)
        ra = srv.submit(a, max_new_tokens=10)
        rb = srv.submit(b, max_new_tokens=5)
        assert srv.cancel(rb) is True          # still queued
        for _ in range(3):
            srv.step()                         # a is mid-decode
        assert srv.cancel(ra) is True
        outs = srv.run()
        assert rb not in outs
        partial = outs[ra]
        want = _solo(model, a, 10)
        assert 1 <= len(partial) < 10
        np.testing.assert_array_equal(partial, want[:len(partial)])
        assert srv.cancel(12345) is False

    def test_threaded_serving_solo_exact(self):
        """start() drives decode on a background thread; concurrent
        submitters get solo-exact results via wait()."""
        import threading
        model = _model()
        rng = np.random.default_rng(12)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 6, 5, 7)]
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64).start()
        results = {}
        errs = []

        def client(i, p):
            try:
                rid = srv.submit(p, max_new_tokens=5)
                results[i] = srv.wait(rid, timeout=300)
            except Exception as e:     # surface in main thread
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i, p))
                   for i, p in enumerate(prompts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        srv.stop()
        assert not errs, errs
        assert len(results) == 4
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(results[i], _solo(model, p, 5))

    def test_poisoned_callback_fails_only_its_request(self):
        """code-review r5 + PR 3 supervision: a crashing on_token
        callback must not wedge (or kill) the server — ITS waiter gets
        the typed error, and the server keeps serving new requests on
        the same thread."""
        from paddle_tpu.reliability import CallbackError
        model = _model()
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=64).start()
        rid = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=4,
                         on_token=lambda r, t: 1 / 0)
        with pytest.raises(CallbackError, match="on_token"):
            srv.wait(rid, timeout=60)
        # the serve thread survived: a fresh request completes normally
        p = np.arange(4, dtype=np.int32)
        rid2 = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.wait(rid2, timeout=300),
                                      _solo(model, p, 4))
        srv.stop()

    def test_everything_composed(self):
        """Kitchen sink: prefix cache + chunked prefill + tick_block +
        weight-only int8, all at once — still solo-parity."""
        model = _model()
        rng = np.random.default_rng(9)
        prefix = rng.integers(0, 256, (8,)).astype(np.int32)
        tails = [rng.integers(0, 256, (n,)).astype(np.int32)
                 for n in (3, 6)]
        prompts = [np.concatenate([prefix, t]) for t in tails] + \
                  [rng.integers(0, 256, (5,)).astype(np.int32)]
        srv = ContinuousBatchingServer(
            model, max_slots=2, max_cache_len=64, weight_dtype="int8",
            prefill_chunk=4, tick_block=3)
        srv.register_prefix(prefix)
        rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
        outs = srv.run()
        for rid, p in zip(rids, prompts):
            want = model.generate(pt.to_tensor(p[None]),
                                  max_new_tokens=6, max_cache_len=64,
                                  weight_dtype="int8",
                                  prefill_chunk=4).numpy()[0, len(p):]
            np.testing.assert_array_equal(outs[rid], want)
        assert srv.stats["prefix_hit_tokens"] == 16

    def test_gpt_greedy_parity_through_server(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(22)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, model.cfg.vocab_size, (n,))
                   .astype(np.int32) for n in (3, 4)]
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64)
        rids = [srv.submit(p, max_new_tokens=5) for p in prompts]
        outs = srv.run()
        for rid, prompt in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid],
                                          _solo(model, prompt, 5))
