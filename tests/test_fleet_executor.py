"""FleetExecutor task-graph layer (reference fleet_executor_utils.py +
the C++ actor runtime, collapsed to an in-process drain on TPU)."""
import numpy as np

from paddle_tpu.parallel.fleet_executor import (CoordSys, FleetExecutor,
                                                FleetExecutorUtils,
                                                TaskNode)


def test_coord_sys_matches_reference_math():
    cs = CoordSys({"dp_degree": 2, "pp_degree": 2, "sharding_degree": 1,
                   "mp_degree": 2})
    # reference layout: dp outermost, mp innermost
    assert cs.coord_to_rank({"dp_idx": 0, "pp_idx": 0, "sharding_idx": 0,
                             "mp_idx": 1}) == 1
    assert cs.coord_to_rank({"dp_idx": 1, "pp_idx": 0, "sharding_idx": 0,
                             "mp_idx": 0}) == 4
    assert cs.coord_to_rank({"dp_idx": 0, "pp_idx": 2, "sharding_idx": 0,
                             "mp_idx": 0}) == -1      # invalid coord
    for r in range(8):
        assert cs.coord_to_rank(cs.rank_to_coord(r)) == r


def test_build_1f1b_dependency_edges():
    strat = {"dp_degree": 1, "pp_degree": 2, "sharding_degree": 1,
             "mp_degree": 1}
    # middle of the pipe: rank 0 (first stage), rank 1 (last stage)
    u0 = FleetExecutorUtils(strat, rank=0, nrank=2, max_run_times=4)
    n0 = u0.build_1f1b_dependency(u0.construct_task_nodes_1f1b({}))
    u1 = FleetExecutorUtils(strat, rank=1, nrank=2, max_run_times=4)
    n1 = u1.build_1f1b_dependency(u1.construct_task_nodes_1f1b({}))
    # rank 0: lr=0 fwd=1 bwd=2 opt=3; rank 1: lr=4 fwd=5 bwd=6 opt=7
    assert n0["fwd"].downstreams == {2: 2, 5: 2}   # own bwd + next fwd
    assert n0["fwd"].upstreams == {0: 2}           # first stage: lr only
    assert n0["bwd"].upstreams == {1: 2, 6: 2}     # own fwd + next bwd
    # pp buffer size = pp_degree - pp_idx (in-flight microbatches)
    assert n0["fwd"].downstreams[2] == 2 and n1["fwd"].downstreams[6] == 1
    assert n1["fwd"].upstreams == {4: 2, 1: 2}     # own lr + prev fwd
    assert u0.task_id_to_rank()[6] == 1


def test_fleet_executor_runs_1f1b_order():
    strat = {"dp_degree": 1, "pp_degree": 2, "sharding_degree": 1,
             "mp_degree": 1}
    M = 4
    log = []
    nodes = []
    for rank in range(2):
        u = FleetExecutorUtils(strat, rank=rank, nrank=2, max_run_times=M)
        names = ("lr", "fwd", "bwd", "opt")
        progs = {n: (lambda mb, n=n, r=rank: log.append((r, n, mb)))
                 for n in names}
        tmap = u.build_1f1b_dependency(u.construct_task_nodes_1f1b(progs))
        nodes.extend(tmap.values())
    fe = FleetExecutor(nodes, max_run_times=M)
    trace = fe.run()
    # every functionality ran M microbatches
    assert len(trace) == 2 * 4 * M
    # causality: stage-1 fwd of microbatch k after stage-0 fwd of k;
    # opt after all bwd microbatches' predecessors
    def pos(r, n, mb):
        return log.index((r, n, mb))
    for mb in range(M):
        assert pos(1, "fwd", mb) > pos(0, "fwd", mb)
        assert pos(0, "bwd", mb) > pos(1, "bwd", mb)
    # 1F1B buffer bound: stage 0 never has more than pp_degree fwd
    # microbatches ahead of its bwd
    f0 = [log.index((0, "fwd", mb)) for mb in range(M)]
    b0 = [log.index((0, "bwd", mb)) for mb in range(M)]
    assert f0[2] > b0[0] - 0  # fwd mb2 can't start before bwd mb0 frees a slot


def test_fleet_executor_detects_deadlock():
    import pytest
    a = TaskNode(task_id=0, max_run_times=1)
    b = TaskNode(task_id=1, max_run_times=1)
    a.add_upstream_task(1)
    b.add_upstream_task(0)      # cycle with no producer
    a.add_downstream_task(1)
    b.add_downstream_task(0)
    with pytest.raises(RuntimeError, match="deadlock"):
        FleetExecutor([a, b], max_run_times=1).run()
