"""FleetExecutor task-graph layer (reference fleet_executor_utils.py +
the C++ actor runtime, collapsed to an in-process drain on TPU)."""
import numpy as np

from paddle_tpu.parallel.fleet_executor import (CoordSys, FleetExecutor,
                                                FleetExecutorUtils,
                                                TaskNode)


def test_coord_sys_matches_reference_math():
    cs = CoordSys({"dp_degree": 2, "pp_degree": 2, "sharding_degree": 1,
                   "mp_degree": 2})
    # reference layout: dp outermost, mp innermost
    assert cs.coord_to_rank({"dp_idx": 0, "pp_idx": 0, "sharding_idx": 0,
                             "mp_idx": 1}) == 1
    assert cs.coord_to_rank({"dp_idx": 1, "pp_idx": 0, "sharding_idx": 0,
                             "mp_idx": 0}) == 4
    assert cs.coord_to_rank({"dp_idx": 0, "pp_idx": 2, "sharding_idx": 0,
                             "mp_idx": 0}) == -1      # invalid coord
    for r in range(8):
        assert cs.coord_to_rank(cs.rank_to_coord(r)) == r


def test_build_1f1b_dependency_edges():
    strat = {"dp_degree": 1, "pp_degree": 2, "sharding_degree": 1,
             "mp_degree": 1}
    # middle of the pipe: rank 0 (first stage), rank 1 (last stage)
    u0 = FleetExecutorUtils(strat, rank=0, nrank=2, max_run_times=4)
    n0 = u0.build_1f1b_dependency(u0.construct_task_nodes_1f1b({}))
    u1 = FleetExecutorUtils(strat, rank=1, nrank=2, max_run_times=4)
    n1 = u1.build_1f1b_dependency(u1.construct_task_nodes_1f1b({}))
    # rank 0: lr=0 fwd=1 bwd=2 opt=3; rank 1: lr=4 fwd=5 bwd=6 opt=7
    assert n0["fwd"].downstreams == {2: 2, 5: 2}   # own bwd + next fwd
    assert n0["fwd"].upstreams == {0: 2}           # first stage: lr only
    assert n0["bwd"].upstreams == {1: 2, 6: 2}     # own fwd + next bwd
    # pp buffer size = pp_degree - pp_idx (in-flight microbatches)
    assert n0["fwd"].downstreams[2] == 2 and n1["fwd"].downstreams[6] == 1
    assert n1["fwd"].upstreams == {4: 2, 1: 2}     # own lr + prev fwd
    assert u0.task_id_to_rank()[6] == 1


def test_fleet_executor_runs_1f1b_order():
    strat = {"dp_degree": 1, "pp_degree": 2, "sharding_degree": 1,
             "mp_degree": 1}
    M = 4
    log = []
    nodes = []
    for rank in range(2):
        u = FleetExecutorUtils(strat, rank=rank, nrank=2, max_run_times=M)
        names = ("lr", "fwd", "bwd", "opt")
        progs = {n: (lambda mb, n=n, r=rank: log.append((r, n, mb)))
                 for n in names}
        tmap = u.build_1f1b_dependency(u.construct_task_nodes_1f1b(progs))
        nodes.extend(tmap.values())
    fe = FleetExecutor(nodes, max_run_times=M)
    trace = fe.run()
    # every functionality ran M microbatches
    assert len(trace) == 2 * 4 * M
    # causality: stage-1 fwd of microbatch k after stage-0 fwd of k;
    # opt after all bwd microbatches' predecessors
    def pos(r, n, mb):
        return log.index((r, n, mb))
    for mb in range(M):
        assert pos(1, "fwd", mb) > pos(0, "fwd", mb)
        assert pos(0, "bwd", mb) > pos(1, "bwd", mb)
    # 1F1B buffer bound: stage 0 never has more than pp_degree fwd
    # microbatches ahead of its bwd
    f0 = [log.index((0, "fwd", mb)) for mb in range(M)]
    b0 = [log.index((0, "bwd", mb)) for mb in range(M)]
    assert f0[2] > b0[0] - 0  # fwd mb2 can't start before bwd mb0 frees a slot


def test_fleet_executor_detects_deadlock():
    import pytest
    a = TaskNode(task_id=0, max_run_times=1)
    b = TaskNode(task_id=1, max_run_times=1)
    a.add_upstream_task(1)
    b.add_upstream_task(0)      # cycle with no producer
    a.add_downstream_task(1)
    b.add_downstream_task(0)
    with pytest.raises(RuntimeError, match="deadlock"):
        FleetExecutor([a, b], max_run_times=1).run()


# ---------------------------------------------------------- actor runtime

def test_message_bus_protocol_flows():
    """The reference protocol is visible on the bus: DATA_IS_READY flows
    downstream, DATA_IS_USELESS releases upstream, START seeds sources
    (interceptor_message.proto types over carrier.h routing)."""
    from paddle_tpu.parallel.fleet_executor import (
        Carrier, DATA_IS_READY, DATA_IS_USELESS, START)
    a = TaskNode(task_id=0)
    b = TaskNode(task_id=1)
    a.add_downstream_task(1, 2)
    b.add_upstream_task(0, 2)
    car = Carrier([a, b], max_run_times=3)
    trace = car.start()
    # causality: b's microbatch k only after a's microbatch k; a never
    # more than buffer=2 ahead of b
    pos = {(t, m): i for i, (t, m) in enumerate(trace)}
    for k in range(3):
        assert pos[(0, k)] < pos[(1, k)]
    for i, (t, m) in enumerate(trace):
        if t == 0:
            done_b = sum(1 for (t2, _m2) in trace[:i] if t2 == 1)
            assert m - done_b < 2, trace
    kinds = [m.message_type for m in car.bus.log]
    assert kinds.count(START) == 3
    assert kinds.count(DATA_IS_READY) == 3          # a -> b per mb
    assert kinds.count(DATA_IS_USELESS) == 3        # b releases a per mb
    ready = [m for m in car.bus.log if m.message_type == DATA_IS_READY]
    assert all(m.src_id == 0 and m.dst_id == 1 for m in ready)


def test_buffer_size_throttles_producer():
    """A buffer of 1 on a->b forces strict alternation: `a` can never
    run 2 ahead (ComputeInterceptor CanWriteOutput)."""
    from paddle_tpu.parallel.fleet_executor import Carrier
    a, b = TaskNode(task_id=0), TaskNode(task_id=1)
    a.add_downstream_task(1, 1)
    b.add_upstream_task(0, 1)
    car = Carrier([a, b], max_run_times=4)
    trace = car.start()
    for i in range(len(trace) - 1):
        (t1, m1), (t2, m2) = trace[i], trace[i + 1]
        if t1 == 0:
            assert (t2, m2) == (1, m1), trace       # strict a,b,a,b

def test_amplifier_runs_once_per_round():
    """Amplifier nodes (lr/opt in the reference) execute every
    run_per_steps messages at their offset while the dataflow still
    ticks every microbatch (amplifier_interceptor.h)."""
    from paddle_tpu.parallel.fleet_executor import Carrier
    M = 6
    ran = []
    fwd = TaskNode(task_id=0, program=lambda mb: ran.append(("fwd", mb)))
    opt = TaskNode(task_id=1, node_type="Amplifier",
                   program=lambda k: ran.append(("opt", k)))
    opt.set_run_pre_steps(3)       # once per 3 microbatches
    opt.set_run_at_offset(2)       # at the round's last microbatch
    fwd.add_downstream_task(1, 3)
    opt.add_upstream_task(0, 3)
    car = Carrier([fwd, opt], max_run_times=M)
    car.start()
    assert [x for x in ran if x[0] == "opt"] == [("opt", 0), ("opt", 1)]
    assert len([x for x in ran if x[0] == "fwd"]) == M


def test_deadlocked_graph_raises():
    from paddle_tpu.parallel.fleet_executor import Carrier
    a, b = TaskNode(task_id=0), TaskNode(task_id=1)
    # b depends on a AND a depends on b with zero seed -> no source
    a.add_upstream_task(1, 2)
    a.add_downstream_task(1, 2)
    b.add_upstream_task(0, 2)
    b.add_downstream_task(0, 2)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="deadlock"):
        Carrier([a, b], max_run_times=2).start()


def test_one_sided_edge_declarations_mirror():
    """A downstream declared without the matching upstream (or vice
    versa) still gates correctly — the Carrier mirrors one-sided edges
    instead of crashing on undeclared peers."""
    from paddle_tpu.parallel.fleet_executor import Carrier
    a, b = TaskNode(task_id=0), TaskNode(task_id=1)
    a.add_downstream_task(1, 2)       # b never declares the upstream
    trace = Carrier([a, b], max_run_times=2).start()
    pos = {(t, m): i for i, (t, m) in enumerate(trace)}
    assert pos[(0, 0)] < pos[(1, 0)] and pos[(0, 1)] < pos[(1, 1)]


def test_executor_count_overrides_node_count():
    """The executor-level max_run_times drives the run (old-contract
    parity): a node constructed with a larger count neither over-runs
    nor deadlocks, and the caller's TaskNode is not mutated."""
    a = TaskNode(task_id=0, max_run_times=5)
    b = TaskNode(task_id=1)
    a.add_downstream_task(1, 2)
    b.add_upstream_task(0, 2)
    fe = FleetExecutor([a, b], max_run_times=2)
    trace = fe.run()
    assert sorted(trace) == [(0, 0), (0, 1), (1, 0), (1, 1)]
    assert a.max_run_times == 5       # caller's object untouched
