"""Round-3 functional tail: CTC, grid_sample, fold/unfold family, loss zoo
(torch-CPU oracles, reference python/paddle/nn/functional/{loss,vision}.py).
"""
import numpy as np
import pytest
import torch

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def test_ctc_loss_matches_torch(rng):
    T, N, C, S = 12, 3, 5, 4
    logits = rng.normal(size=(T, N, C)).astype("float32")
    labels = rng.integers(1, C, size=(N, S)).astype("int32")
    il = np.array([12, 10, 8], "int32")
    ll = np.array([4, 3, 2], "int32")
    mine = F.ctc_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                      pt.to_tensor(il), pt.to_tensor(ll), blank=0,
                      reduction="none")
    ref = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits), -1),
        torch.tensor(labels.astype("int64")),
        torch.tensor(il.astype("int64")),
        torch.tensor(ll.astype("int64")), blank=0, reduction="none")
    np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_ctc_loss_grad_finite(rng):
    logits = pt.to_tensor(rng.normal(size=(6, 2, 4)).astype("float32"),
                          stop_gradient=False)
    labels = pt.to_tensor(np.array([[1, 2], [3, 1]], "int32"))
    il = pt.to_tensor(np.array([6, 5], "int32"))
    ll = pt.to_tensor(np.array([2, 2], "int32"))
    loss = F.ctc_loss(logits, labels, il, ll)
    loss.backward()
    assert np.isfinite(logits.grad.numpy()).all()


def test_grid_sample_matches_torch(rng):
    x = rng.normal(size=(2, 3, 5, 6)).astype("float32")
    grid = rng.uniform(-1, 1, size=(2, 4, 4, 2)).astype("float32")
    for align in (True, False):
        mine = F.grid_sample(pt.to_tensor(x), pt.to_tensor(grid),
                             align_corners=align)
        ref = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(grid), align_corners=align,
            padding_mode="zeros")
        np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)


def test_grid_sample_nearest(rng):
    x = rng.normal(size=(1, 2, 4, 4)).astype("float32")
    grid = rng.uniform(-1, 1, size=(1, 3, 3, 2)).astype("float32")
    mine = F.grid_sample(pt.to_tensor(x), pt.to_tensor(grid),
                         mode="nearest", align_corners=True)
    ref = torch.nn.functional.grid_sample(
        torch.tensor(x), torch.tensor(grid), mode="nearest",
        align_corners=True, padding_mode="zeros")
    np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_fold_matches_torch(rng):
    cols = rng.normal(size=(2, 3 * 2 * 2, 4)).astype("float32")
    mine = F.fold(pt.to_tensor(cols), (4, 4), (2, 2), strides=2)
    ref = torch.nn.functional.fold(torch.tensor(cols), (4, 4), (2, 2),
                                   stride=2)
    np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_max_unpool2d_matches_torch(rng):
    xp = torch.tensor(rng.normal(size=(1, 2, 4, 4)).astype("float32"))
    pooled, idx = torch.nn.functional.max_pool2d(xp, 2, return_indices=True)
    ref = torch.nn.functional.max_unpool2d(pooled, idx, 2)
    mine = F.max_unpool2d(pt.to_tensor(pooled.numpy()),
                          pt.to_tensor(idx.numpy()), 2)
    np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-5)


def test_loss_zoo_finite_and_reference(rng):
    a = rng.normal(size=(4, 5)).astype("float32")
    b = rng.normal(size=(4, 5)).astype("float32")
    ta, tb = pt.to_tensor(a), pt.to_tensor(b)
    # huber == torch huber
    np.testing.assert_allclose(
        float(F.huber_loss(ta, tb, delta=1.0).numpy()),
        float(torch.nn.functional.huber_loss(torch.tensor(a),
                                             torch.tensor(b))), rtol=1e-5)
    # soft margin == torch
    y = np.sign(b).astype("float32")
    np.testing.assert_allclose(
        float(F.soft_margin_loss(ta, pt.to_tensor(y)).numpy()),
        float(torch.nn.functional.soft_margin_loss(torch.tensor(a),
                                                   torch.tensor(y))),
        rtol=1e-5)
    # gaussian nll == torch
    var = (np.abs(rng.normal(size=(4, 5))) + 0.1).astype("float32")
    np.testing.assert_allclose(
        float(F.gaussian_nll_loss(ta, tb, pt.to_tensor(var)).numpy()),
        float(torch.nn.functional.gaussian_nll_loss(
            torch.tensor(a), torch.tensor(b), torch.tensor(var))),
        rtol=1e-4, atol=1e-5)
    # poisson nll == torch
    lbl = np.abs(b).astype("float32")
    np.testing.assert_allclose(
        float(F.poisson_nll_loss(ta, pt.to_tensor(lbl)).numpy()),
        float(torch.nn.functional.poisson_nll_loss(
            torch.tensor(a), torch.tensor(lbl))), rtol=1e-4)
    # multi-label soft margin == torch
    ml = (rng.random((4, 5)) > 0.5).astype("float32")
    np.testing.assert_allclose(
        float(F.multi_label_soft_margin_loss(ta, pt.to_tensor(ml)).numpy()),
        float(torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(a), torch.tensor(ml))), rtol=1e-4)
    # triplet with distance == torch
    n = rng.normal(size=(4, 5)).astype("float32")
    np.testing.assert_allclose(
        float(F.triplet_margin_with_distance_loss(
            ta, tb, pt.to_tensor(n)).numpy()),
        float(torch.nn.functional.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(b), torch.tensor(n))), rtol=1e-4)


def test_pixel_channel_ops(rng):
    x = rng.normal(size=(2, 4, 4, 4)).astype("float32")
    un = F.pixel_unshuffle(pt.to_tensor(x), 2)
    ref = torch.nn.functional.pixel_unshuffle(torch.tensor(x), 2)
    np.testing.assert_allclose(un.numpy(), ref.numpy(), rtol=1e-6)
    cs = F.channel_shuffle(pt.to_tensor(x), 2)
    ref = torch.nn.functional.channel_shuffle(torch.tensor(x), 2)
    np.testing.assert_allclose(cs.numpy(), ref.numpy(), rtol=1e-6)
    zp = F.zeropad2d(pt.to_tensor(x), (1, 2, 3, 4))
    assert list(zp.shape) == [2, 4, 4 + 7, 4 + 3]


def test_pairwise_distance(rng):
    a = rng.normal(size=(4, 5)).astype("float32")
    b = rng.normal(size=(4, 5)).astype("float32")
    mine = F.pairwise_distance(pt.to_tensor(a), pt.to_tensor(b))
    ref = torch.nn.functional.pairwise_distance(torch.tensor(a),
                                                torch.tensor(b))
    np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-4)


def test_huber_loss_delta_scaling(rng):
    a = rng.normal(size=(4, 5)).astype("float32") * 3
    b = rng.normal(size=(4, 5)).astype("float32")
    for delta in (0.5, 2.0):
        mine = float(F.huber_loss(pt.to_tensor(a), pt.to_tensor(b),
                                  delta=delta).numpy())
        ref = float(torch.nn.functional.huber_loss(
            torch.tensor(a), torch.tensor(b), delta=delta))
        np.testing.assert_allclose(mine, ref, rtol=1e-5)


def test_ctc_loss_empty_target(rng):
    T, N, C = 8, 2, 4
    logits = rng.normal(size=(T, N, C)).astype("float32")
    labels = np.array([[1, 2], [0, 0]], "int32")
    il = np.array([8, 8], "int32")
    ll = np.array([2, 0], "int32")   # second sample: empty target
    mine = F.ctc_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                      pt.to_tensor(il), pt.to_tensor(ll), reduction="none")
    ref = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits), -1),
        torch.tensor(labels.astype("int64")),
        torch.tensor(il.astype("int64")),
        torch.tensor(ll.astype("int64")), reduction="none",
        zero_infinity=False)
    np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_grid_sample_reflection_and_border(rng):
    x = rng.normal(size=(1, 2, 5, 5)).astype("float32")
    grid = rng.uniform(-1.6, 1.6, size=(1, 4, 4, 2)).astype("float32")
    for pm in ("reflection", "border"):
        mine = F.grid_sample(pt.to_tensor(x), pt.to_tensor(grid),
                             padding_mode=pm, align_corners=True)
        ref = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(grid), padding_mode=pm,
            align_corners=True)
        np.testing.assert_allclose(mine.numpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)
    with pytest.raises(ValueError):
        F.grid_sample(pt.to_tensor(x), pt.to_tensor(grid),
                      padding_mode="bogus")


def test_lu_unpack_batched(rng):
    a = rng.normal(size=(3, 4, 4)).astype("float32")
    ta = torch.tensor(a)
    lu, piv = torch.linalg.lu_factor(ta)
    P, L, U = torch.lu_unpack(lu, piv)
    import paddle_tpu.ops.linalg as lin
    mp, ml, mu = pt.ops.lu_unpack(pt.to_tensor(lu.numpy()),
                                  pt.to_tensor(piv.numpy().astype("int32")))
    np.testing.assert_allclose(mp.numpy(), P.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ml.numpy(), L.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(mu.numpy(), U.numpy(), rtol=1e-5, atol=1e-5)


def test_fused_linear_cross_entropy_matches_reference(rng):
    """ops/fused_ce.py: chunked linear+CE == materialized logits CE,
    values and grads (the bench.py PT_BENCH_FUSED_CE path)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy
    T, H, V = 48, 16, 50
    h = jnp.asarray(rng.normal(size=(T, H)).astype("float32"))
    w = jnp.asarray(rng.normal(size=(H, V)).astype("float32") * 0.1)
    l = jnp.asarray(rng.integers(0, V, T).astype("int32"))

    def ref(h, w):
        lp = jax.nn.log_softmax((h @ w).astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, l[:, None], -1).mean()

    def fused(h, w):
        return fused_linear_cross_entropy(h, w, l, chunk_size=12)

    lr, gr = jax.value_and_grad(ref, argnums=(0, 1))(h, w)
    lf, gf = jax.value_and_grad(fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(lr), float(lf), rtol=1e-6)
    np.testing.assert_allclose(gr[0], gf[0], rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gr[1], gf[1], rtol=1e-4, atol=1e-6)
    # non-dividing chunk -> single-chunk fallback still correct
    lf2 = fused_linear_cross_entropy(h, w, l, chunk_size=13)
    np.testing.assert_allclose(float(lr), float(lf2), rtol=1e-6)
    # sum reduction + 3D input form
    l3 = fused_linear_cross_entropy(h.reshape(4, 12, H), w,
                                    l.reshape(4, 12), chunk_size=12,
                                    reduction="sum")
    np.testing.assert_allclose(float(l3), float(lr) * T, rtol=1e-6)


def _rnnt_case(rng):
    B, T, U, C = 2, 4, 3, 5
    logits = rng.standard_normal((B, T, U + 1, C)).astype("float32")
    labels = rng.integers(1, C, (B, U)).astype("int32")
    tl = np.full((B,), T, "int32")
    ul = np.full((B,), U, "int32")

    def loss_fn(lam):
        lg = pt.to_tensor(logits, stop_gradient=False)
        out = F.rnnt_loss(lg, pt.to_tensor(labels), pt.to_tensor(tl),
                          pt.to_tensor(ul), blank=0, fastemit_lambda=lam,
                          reduction="sum")
        out.backward()
        return float(out), np.asarray(lg.grad.numpy())

    return logits, labels, tl, ul, loss_fn


def test_rnnt_loss_fastemit(rng):
    # FastEmit (ADVICE r3 fix): loss value unchanged; label-emission
    # gradient scaled by (1 + lambda).
    _logits, _labels, _tl, _ul, loss_fn = _rnnt_case(rng)
    v0, g0 = loss_fn(0.0)
    v1, g1 = loss_fn(0.5)
    np.testing.assert_allclose(v0, v1, rtol=1e-5)     # value unchanged
    assert not np.allclose(g0, g1)                     # gradient differs


def test_rnnt_loss_fastemit_torchaudio(rng):
    ta = pytest.importorskip("torchaudio")
    logits, labels, tl, ul, loss_fn = _rnnt_case(rng)
    for lam in (0.0, 0.5):
        tlg = torch.tensor(logits, requires_grad=True)
        tloss = ta.functional.rnnt_loss(
            tlg, torch.tensor(labels), torch.tensor(tl), torch.tensor(ul),
            blank=0, fastemit_lambda=lam, reduction="sum")
        tloss.backward()
        _v, g = loss_fn(lam)
        np.testing.assert_allclose(_v, float(tloss), rtol=1e-4)
        np.testing.assert_allclose(g, tlg.grad.numpy(), rtol=1e-3,
                                   atol=1e-4)
