"""Native C++ runtime tests: TCPStore rendezvous + flags registry."""
import threading

import pytest


def test_build_and_flags():
    from paddle_tpu import runtime
    runtime.set_flags({"FLAGS_check_nan_inf": "1",
                       "allocator_strategy": "auto_growth"})
    out = runtime.get_flags(["FLAGS_check_nan_inf", "FLAGS_missing"])
    assert out["FLAGS_check_nan_inf"] == "1"
    assert out["FLAGS_missing"] is None
    assert runtime.list_flags()["allocator_strategy"] == "auto_growth"


def test_tcp_store_set_get_add():
    from paddle_tpu.runtime import TCPStore
    master = TCPStore(is_master=True, port=0)
    client = TCPStore(host="127.0.0.1", port=master.port)
    master.set("unique_id", b"\x01\x02\x03nccl-equivalent")
    assert client.get("unique_id") == b"\x01\x02\x03nccl-equivalent"
    assert client.add("counter", 5) == 5
    assert master.add("counter", 2) == 7
    assert client.check("unique_id")
    assert not client.check("nope")
    client.delete_key("unique_id")
    assert not master.check("unique_id")
    client.close()
    master.close()


def test_tcp_store_blocking_get_and_barrier():
    from paddle_tpu.runtime import TCPStore
    master = TCPStore(is_master=True, port=0)
    results = {}

    def waiter():
        c = TCPStore(port=master.port)
        results["v"] = c.get("late_key")  # blocks until set
        c.close()

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.2)
    master.set("late_key", b"arrived")
    t.join(timeout=5)
    assert results["v"] == b"arrived"

    # 3-party barrier
    def member(i):
        c = TCPStore(port=master.port)
        c.barrier("b0", 3)
        c.close()

    ts = [threading.Thread(target=member, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    master.barrier("b0", 3)
    for t in ts:
        t.join(timeout=5)
    master.close()
