"""Debug/aux subsystem tests: nan/inf sanitizer, fused softmax mask ops,
auto-checkpoint, run_check, memory stats."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


class TestNanInfCheck:
    def teardown_method(self):
        from paddle_tpu.core.tensor import set_nan_inf_check
        set_nan_inf_check(False)

    def test_raises_on_nan(self):
        from paddle_tpu import runtime
        runtime.set_flags({"FLAGS_check_nan_inf": 1})
        x = pt.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match="Inf/Nan"):
            pt.log(x - 1.0)  # log(-1) and log(0)

    def test_warn_level(self):
        from paddle_tpu import runtime
        runtime.set_flags({"FLAGS_check_nan_inf": 1,
                           "FLAGS_check_nan_inf_level": 1})
        x = pt.to_tensor(np.array([-1.0], np.float32))
        with pytest.warns(UserWarning, match="Inf/Nan"):
            pt.sqrt(x)

    def test_off_by_default(self):
        from paddle_tpu import runtime
        runtime.set_flags({"FLAGS_check_nan_inf": 0})
        x = pt.to_tensor(np.array([-1.0], np.float32))
        out = pt.sqrt(x)  # silently nan, like the reference default
        assert np.isnan(out.numpy()).all()

    def test_checks_grad_path_outputs(self):
        from paddle_tpu import runtime
        runtime.set_flags({"FLAGS_check_nan_inf": 1,
                           "FLAGS_check_nan_inf_level": 0})
        x = pt.to_tensor(np.array([0.0], np.float32),
                         stop_gradient=False)
        with pytest.raises(FloatingPointError):
            pt.ops.OPS["divide"](pt.to_tensor(np.float32(1.0)), x)


class TestFusedSoftmaxMask:
    def test_softmax_mask_fuse(self):
        from paddle_tpu import incubate
        rng = np.random.RandomState(0)
        x = rng.randn(2, 2, 4, 4).astype(np.float32)
        mask = np.where(rng.rand(2, 1, 4, 4) < 0.3, -10000.0,
                        0.0).astype(np.float32)
        out = incubate.softmax_mask_fuse(pt.to_tensor(x),
                                         pt.to_tensor(mask)).numpy()
        e = np.exp((x + mask) - (x + mask).max(-1, keepdims=True))
        ref = e / e.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_softmax_mask_fuse_upper_triangle(self):
        rng = np.random.RandomState(0)
        from paddle_tpu import incubate
        x = rng.randn(1, 2, 5, 5).astype(np.float32)
        out = incubate.softmax_mask_fuse_upper_triangle(
            pt.to_tensor(x)).numpy()
        # rows softmax over the causal prefix; strictly-upper entries 0
        for i in range(5):
            row = out[0, 0, i]
            assert np.allclose(row[i + 1:], 0)
            np.testing.assert_allclose(row[:i + 1].sum(), 1.0, rtol=1e-5)

    def test_upper_triangle_grad_flows(self):
        x = pt.to_tensor(np.random.randn(1, 1, 3, 3).astype(np.float32),
                         stop_gradient=False)
        from paddle_tpu import incubate
        out = incubate.softmax_mask_fuse_upper_triangle(x)
        pt.ops.OPS["sum"](out).backward()
        assert x.grad is not None


class TestAutoCheckpoint:
    def test_resume_after_interrupt(self):
        from paddle_tpu.incubate.checkpoint import TrainEpochRange
        d = tempfile.mkdtemp()
        model = nn.Linear(4, 4)
        opt = pt.optimizer.AdamW(parameters=model.parameters())

        r1 = TrainEpochRange(5, "job", checkpoint_dir=d)
        r1.add("model", model).add("opt", opt)
        seen = []
        for epoch in r1:
            seen.append(epoch)
            if epoch == 2:
                break  # simulated preemption AFTER e2 save? break skips save
        # epochs 0,1 were saved (save happens after yield); e2 not saved
        assert seen == [0, 1, 2]

        model2 = nn.Linear(4, 4)
        opt2 = pt.optimizer.AdamW(parameters=model2.parameters())
        r2 = TrainEpochRange(5, "job", checkpoint_dir=d)
        r2.add("model", model2).add("opt", opt2)
        assert r2.restored_from() == 1
        rest = list(r2)
        assert rest == [2, 3, 4]
        # restored weights equal the e1 snapshot of the original model
        np.testing.assert_allclose(model2.weight.numpy(),
                                   model.weight.numpy())


def test_run_check_and_memory_stats():
    pt.utils.run_check()
    from paddle_tpu import device
    stats = device.memory_stats()
    assert isinstance(stats, dict)
