"""Meta-optimizer zoo + recompute + LARS tests (reference
test_fleet_gradient_merge_meta_optimizer.py / localsgd / dgc /
test_fleet_lars_meta_optimizer.py patterns, eager-style)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.parallel.meta_optimizers import (
    DGCOptimizer, GradientMergeOptimizer, LocalSGDOptimizer,
    RecomputeOptimizer, apply_strategy_meta_optimizers)


def _model_and_data(seed=0):
    pt.seed(seed)
    rng = np.random.RandomState(seed)
    model = nn.Linear(4, 3)
    x = pt.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = pt.to_tensor(rng.randint(0, 3, size=(8,)))
    return model, x, y


def _loss_step(model, x, y):
    loss = nn.functional.cross_entropy(model(x), y)
    loss.backward()
    return float(loss.numpy())


class TestGradientMerge:
    def test_applies_every_k_steps(self):
        model, x, y = _model_and_data()
        inner = pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=model.parameters())
        opt = GradientMergeOptimizer(inner, k_steps=2, avg=True)
        w0 = model.weight.numpy().copy()
        _loss_step(model, x, y)
        opt.step()
        opt.clear_grad()
        np.testing.assert_allclose(model.weight.numpy(), w0)  # no update yet
        _loss_step(model, x, y)
        opt.step()
        opt.clear_grad()
        assert not np.allclose(model.weight.numpy(), w0)      # applied

    def test_merged_equals_full_batch(self):
        """Two half-batch merged steps == one full-batch step (the defining
        property of gradient merge, reference TestDistBase tolerance)."""
        modelA, x, y = _model_and_data()
        modelB = nn.Linear(4, 3)
        modelB.set_state_dict(modelA.state_dict())
        # A: one step on the full batch
        optA = pt.optimizer.SGD(learning_rate=0.1,
                                parameters=modelA.parameters())
        loss = nn.functional.cross_entropy(modelA(x), y)
        loss.backward()
        optA.step()
        # B: two merged micro-steps on the halves
        optB = GradientMergeOptimizer(
            pt.optimizer.SGD(learning_rate=0.1,
                             parameters=modelB.parameters()), k_steps=2)
        import jax.numpy as jnp
        for sl in (slice(0, 4), slice(4, 8)):
            xs = pt.to_tensor(x.numpy()[sl])
            ys = pt.to_tensor(y.numpy()[sl])
            li = nn.functional.cross_entropy(modelB(xs), ys)
            li.backward()
            optB.step()
            optB.clear_grad()
        np.testing.assert_allclose(modelA.weight.numpy(),
                                   modelB.weight.numpy(), rtol=1e-5,
                                   atol=1e-6)


class TestDGC:
    def test_sparsifies_with_error_feedback(self):
        model, x, y = _model_and_data()
        inner = pt.optimizer.SGD(learning_rate=0.1,
                                 parameters=model.parameters())
        opt = DGCOptimizer(inner, rampup_begin_step=0, sparsity=0.75)
        _loss_step(model, x, y)
        g_before = model.weight.grad.numpy().copy()
        opt.step()
        # residual buffer holds the unsent mass
        res = opt._residual[id(model.weight)]
        nz = int((np.asarray(res) != 0).sum())
        assert nz > 0  # something was withheld
        # and training still converges
        losses = []
        for _ in range(20):
            losses.append(_loss_step(model, x, y))
            opt.step()
            opt.clear_grad()
        assert losses[-1] < losses[0]

    def test_rampup_defers_compression(self):
        model, x, y = _model_and_data()
        opt = DGCOptimizer(
            pt.optimizer.SGD(learning_rate=0.1,
                             parameters=model.parameters()),
            rampup_begin_step=100, sparsity=0.99)
        _loss_step(model, x, y)
        opt.step()
        assert not opt._residual  # dense until rampup


class TestLocalSGDAndRecompute:
    def test_localsgd_steps(self):
        model, x, y = _model_and_data()
        opt = LocalSGDOptimizer(
            pt.optimizer.SGD(learning_rate=0.1,
                             parameters=model.parameters()), k_steps=2)
        w0 = model.weight.numpy().copy()
        for _ in range(4):
            _loss_step(model, x, y)
            opt.step()
            opt.clear_grad()
        assert not np.allclose(model.weight.numpy(), w0)

    def test_recompute_eager_matches_plain(self):
        from paddle_tpu.parallel import recompute
        model, x, y = _model_and_data()
        ref = model(x).numpy()
        out = recompute(model, x)
        np.testing.assert_allclose(out.numpy(), ref)

    def test_recompute_traced_uses_checkpoint(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.parallel import recompute

        def f(v):
            return recompute(lambda u: jnp.sin(u) * 2.0, v).sum()

        g = jax.grad(f)(jnp.ones((4,)))
        np.testing.assert_allclose(np.asarray(g), 2.0 * np.cos(1.0),
                                   rtol=1e-6)

    def test_recompute_sequential(self):
        import jax.numpy as jnp
        from paddle_tpu.parallel import recompute_sequential
        fns = [lambda v: v + 1.0, lambda v: v * 2.0, lambda v: v - 3.0]
        out = recompute_sequential({"segments": 2}, fns, jnp.ones((2,)))
        np.testing.assert_allclose(np.asarray(out), 1.0)  # ((1+1)*2)-3


class TestStrategyComposition:
    def test_apply_strategy_stacks_wrappers(self):
        from paddle_tpu.parallel.fleet import DistributedStrategy
        model, _, _ = _model_and_data()
        st = DistributedStrategy()
        st.dgc = True
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": 4}
        st.localsgd = True
        opt = apply_strategy_meta_optimizers(
            pt.optimizer.SGD(parameters=model.parameters()), st)
        # localsgd(gm(dgc(sgd)))
        assert isinstance(opt, LocalSGDOptimizer)
        assert isinstance(opt.inner_opt, GradientMergeOptimizer)
        assert opt.inner_opt.k_steps == 4
        assert isinstance(opt.inner_opt.inner_opt, DGCOptimizer)


class TestLars:
    def test_lars_trains(self):
        model, x, y = _model_and_data()
        opt = pt.optimizer.LarsMomentum(learning_rate=0.1,
                                        parameters=model.parameters())
        losses = []
        for _ in range(15):
            losses.append(_loss_step(model, x, y))
            opt.step()
            opt.clear_grad()
        assert losses[-1] < losses[0]

    def test_lars_trust_ratio_scales_update(self):
        """Update magnitude tracks ||p||/||g|| (definition of LARS)."""
        pt.seed(0)
        p_big = pt.Parameter(np.full((4, 4), 10.0, np.float32))
        p_small = pt.Parameter(np.full((4, 4), 0.1, np.float32))
        g = np.ones((4, 4), np.float32)
        for p in (p_big, p_small):
            p.grad = pt.to_tensor(g)
        opt = pt.optimizer.LarsMomentum(
            learning_rate=1.0, momentum=0.0, lars_weight_decay=0.0,
            parameters=[p_big, p_small])
        before_b, before_s = p_big.numpy().copy(), p_small.numpy().copy()
        opt.step()
        db = np.abs(p_big.numpy() - before_b).mean()
        ds = np.abs(p_small.numpy() - before_s).mean()
        assert db / ds > 50  # big params get proportionally bigger steps


class TestNewMetaOptimizers:
    def _net(self):
        import paddle_tpu as pt
        net = pt.nn.Linear(8, 8)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        return net, opt

    def test_amp_optimizer_scales_and_steps(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.parallel.meta_optimizers import AMPOptimizer
        net, opt = self._net()
        amp_opt = AMPOptimizer(opt, init_loss_scaling=256.0)
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        w0 = net.weight.numpy().copy()
        loss = net(x).mean()
        amp_opt.scale(loss).backward()
        amp_opt.step()
        opt.clear_grad()
        # params moved by the UNSCALED gradient magnitude
        delta = np.abs(net.weight.numpy() - w0).max()
        assert 0 < delta < 1.0, delta

    def test_fp16_allreduce_keeps_training(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.parallel.meta_optimizers import (
            FP16AllReduceOptimizer)
        net, opt = self._net()
        m = FP16AllReduceOptimizer(opt)
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        m.step()
        assert np.isfinite(net.weight.numpy()).all()

    def test_asp_enforces_2_of_4(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.parallel.meta_optimizers import ASPOptimizer
        net, opt = self._net()
        asp = ASPOptimizer(opt, model=net)
        x = pt.to_tensor(np.random.RandomState(0).randn(
            4, 8).astype(np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        asp.step()
        w = net.weight.numpy()
        groups = w.reshape(w.shape[0], -1, 4)
        nz = (np.abs(groups) > 0).sum(-1)
        assert (nz <= 3).all()          # ties may keep an extra entry
        assert (nz >= 1).all()

    def test_strategy_flags_stack_new_wrappers(self):
        from paddle_tpu.parallel.meta_optimizers import (
            AMPOptimizer, ASPOptimizer, apply_strategy_meta_optimizers)

        class S:
            amp = True
            asp = True
        _, opt = self._net()
        wrapped = apply_strategy_meta_optimizers(opt, S())
        # both wrappers must be applied, pipeline outermost order:
        # amp first, then asp wraps it
        assert isinstance(wrapped, ASPOptimizer)
        assert isinstance(wrapped.inner_opt, AMPOptimizer)

    def test_amp_transparent_without_scale(self):
        # review regression: the fleet minimize() path never calls
        # scale(); step() must NOT unscale unscaled grads
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.parallel.meta_optimizers import AMPOptimizer
        net, opt = self._net()
        amp_opt = AMPOptimizer(opt, init_loss_scaling=32768.0)
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        w0 = net.weight.numpy().copy()
        loss = (net(x) ** 2).mean()
        loss.backward()
        amp_opt.step()     # no scale() happened
        delta = np.abs(net.weight.numpy() - w0).max()
        assert delta > 1e-4, "update was shrunk by the loss scale"


    def test_asp_never_prunes_embeddings(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.parallel.meta_optimizers import ASPOptimizer

        class Net(pt.nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = pt.nn.Embedding(16, 8)
                self.fc = pt.nn.Linear(8, 8)

            def forward(self, ids):
                return self.fc(self.emb(ids))

        net = Net()
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        asp = ASPOptimizer(opt, model=net)
        emb0 = net.emb.weight.numpy().copy()
        ids = pt.to_tensor(np.arange(4).astype(np.int64))
        loss = (net(ids) ** 2).mean()
        loss.backward()
        asp.step()
        emb1 = net.emb.weight.numpy()
        # embedding updated by SGD but NOT 2:4-masked: no row may have
        # half its entries exactly zeroed
        groups = emb1.reshape(16, -1, 4)
        assert not ((np.abs(groups) > 0).sum(-1) <= 2).all()
        # while the Linear weight IS masked
        w = net.fc.weight.numpy()
        assert ((np.abs(w.reshape(8, -1, 4)) > 0).sum(-1) <= 3).all()
        del emb0
