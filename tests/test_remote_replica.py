"""Process-isolated replica protocol (ISSUE 12), fast half: every test
here runs the REAL wire protocol over loopback TCP with the
``ReplicaHost`` living on threads in this process — full transport
coverage without process-spawn cost. The spawned-process drills
(SIGKILL, partition storms at scale) live in test_process_fleet.py.

Covers: submit/wait/stream parity over the wire, typed error transit,
deadline re-anchoring, pushed-digest routing reads + the staleness
walk (fresh -> draining -> dead), wire and synthesized evacuation,
router-over-remote routing/failover/rolling-restart, /fleet over
remote snapshots, and the frame-corruption fuzz contract against a
live host."""
import json
import random
import socket
import struct
import threading
import time

import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.remote import ReplicaHost, RemoteReplica
from paddle_tpu.inference.router import ReplicaRouter
from paddle_tpu.reliability import (DeadlineExceeded, FaultInjector,
                                    QueueFullError, RequestCancelled,
                                    TransportError)


def _loopback_available():
    try:
        s = socket.create_server(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


pytestmark = [
    pytest.mark.net,
    pytest.mark.skipif(not _loopback_available(),
                       reason="cannot bind a loopback socket here"),
]


def _server(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 64)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 8)
    return ContinuousBatchingServer(StubModel(), **kw)


@pytest.fixture
def fleet():
    """Tracked hosts/replicas/routers torn down after each test."""
    made = {"hosts": [], "reps": [], "routers": [], "servers": []}

    def host_rep(heartbeat_s=0.02, server_kw=None, rep_kw=None):
        srv = _server(**(server_kw or {}))
        host = ReplicaHost(srv, heartbeat_s=heartbeat_s).start()
        rep = RemoteReplica(host.address, **(rep_kw or {}))
        made["hosts"].append(host)
        made["reps"].append(rep)
        made["servers"].append(srv)
        return host, rep, srv

    made["host_rep"] = host_rep
    yield made
    for router in made["routers"]:
        try:
            router.stop(drain=False, timeout=10, stop_replicas=False)
        except RuntimeError:
            pass
    for rep in made["reps"]:
        rep.close()
    for host in made["hosts"]:
        host.close()
    for srv in made["servers"]:
        if srv._thread is not None:
            try:
                srv.stop(timeout=10)
            except RuntimeError:
                pass


def _prompt(*toks):
    return np.asarray(toks, np.int32)


class TestWireContract:
    def test_submit_wait_bit_exact_with_streaming(self, fleet):
        _, rep, _ = fleet["host_rep"]()
        rep.start()
        chunks = []
        p = _prompt(2, 5, 9)
        rid = rep.submit(p, max_new_tokens=6,
                         on_token=lambda r, t: chunks.append(list(t)))
        out = rep.wait(rid, timeout=20)
        exp = stub_tokens(p, 6)
        np.testing.assert_array_equal(out, exp)
        # the stream delivered every token exactly once, in order
        streamed = [t for c in chunks for t in c]
        assert streamed == list(exp)

    def test_sampled_chain_parity_with_local_server(self, fleet):
        """Seeds resolve client-side semantics identically: the same
        (prompt, seed) on a remote and a local server draw the same
        sampled chain — the requeue-parity foundation."""
        _, rep, _ = fleet["host_rep"](
            server_kw={"do_sample": True, "temperature": 1.3})
        rep.start()
        local = _server(do_sample=True, temperature=1.3)
        p = _prompt(4, 4, 8)
        rid = rep.submit(p, max_new_tokens=8, seed=123)
        got = rep.wait(rid, timeout=20)
        lrid = local.submit(p, max_new_tokens=8, seed=123)
        np.testing.assert_array_equal(got, local.run()[lrid])

    def test_default_seed_reported_to_mirror_matches_server(self, fleet):
        """submit(seed=None): the host must report the SERVER's actual
        resolved default to the client mirror (pins the default-seed
        rule the host mirrors from ContinuousBatchingServer.submit) —
        a drifted copy would silently break synthesized-requeue
        parity."""
        _, rep, srv = fleet["host_rep"](
            server_kw={"do_sample": True, "seed": 31})
        rid = rep.submit(_prompt(2, 2), max_new_tokens=4)   # no seed
        with rep._state_lock:
            mirrored = rep._mirror[rid].seed
        with srv._lock:
            actual = next(i.seed for i in srv._queue if i.rid == rid)
        assert mirrored == actual == 31 + rid

    def test_typed_errors_cross_the_wire(self, fleet):
        _, rep, _ = fleet["host_rep"](
            server_kw={"max_queue": 0, "shed_policy": "reject"})
        with pytest.raises(DeadlineExceeded):
            rep.submit(_prompt(1), max_new_tokens=2, deadline_s=-1)
        with pytest.raises(QueueFullError):
            rep.submit(_prompt(1), max_new_tokens=2)

    def test_cancel_queued_raises_typed(self, fleet):
        _, rep, _ = fleet["host_rep"]()     # serve thread NOT started
        rid = rep.submit(_prompt(3, 1), max_new_tokens=4)
        assert rep.cancel(rid) is True
        with pytest.raises(RequestCancelled):
            rep.wait(rid, timeout=5)

    def test_deadline_reanchors_on_host_clock(self, fleet):
        _, rep, _ = fleet["host_rep"]()     # not started: stays queued
        rid = rep.submit(_prompt(7, 7), max_new_tokens=4,
                         deadline_s=0.1)
        time.sleep(0.2)
        rep.start()
        with pytest.raises(DeadlineExceeded):
            rep.wait(rid, timeout=10)

    def test_wire_evacuate_returns_remaining_deadline(self, fleet):
        _, rep, _ = fleet["host_rep"]()     # not started: stays queued
        def sink(rid_, toks):
            pass

        rid = rep.submit(_prompt(6, 2), max_new_tokens=4,
                         on_token=sink, deadline_s=30.0,
                         priority=2)
        harvested = rep.evacuate()
        assert [h.rid for h in harvested] == [rid]
        h = harvested[0]
        np.testing.assert_array_equal(h.ids, _prompt(6, 2))
        assert h.budget == 4 and h.priority == 2
        assert h.on_token is sink           # reattached from the mirror
        # the absolute deadline was rebuilt from remaining seconds
        assert 25.0 < h.deadline - rep._clock.now() <= 30.0
        # the host's queue is actually empty now
        assert rep._call("stats")["admissions"] == 0

    def test_wait_survives_lost_reply_via_delivery_stash(self, fleet):
        """A wait whose REPLY frame is dropped retries and still gets
        the result: the host stashes deliveries idempotently."""
        from paddle_tpu.inference.transport import NetDrop
        from paddle_tpu.reliability import NET_RECV
        _, rep, _ = fleet["host_rep"]()
        rep.start()
        p = _prompt(5, 5)
        rid = rep.submit(p, max_new_tokens=4)
        out = rep.wait(rid, timeout=20)     # settle server-side first
        np.testing.assert_array_equal(out, stub_tokens(p, 4))
        # now make the client drop the next reply frame: the SECOND
        # wait for the same rid must still return the stashed result
        fi = FaultInjector(seed=2).on(NET_RECV, schedule=[0],
                                      error=NetDrop)
        rep._conn._faults = fi
        out2 = rep._call("wait", rid=rid, timeout=0.5,
                         reply_timeout=5.0)
        assert list(out2) == list(stub_tokens(p, 4))


class TestDigestsAndStaleness:
    def test_routing_reads_come_from_pushed_digest(self, fleet):
        host, rep, srv = fleet["host_rep"]()
        for i in range(3):
            rep.submit(_prompt(1, 1, i + 1), max_new_tokens=2)
        deadline = time.monotonic() + 5
        while rep.queue_depth() != 3:
            assert time.monotonic() < deadline, "digest never refreshed"
            time.sleep(0.01)
        assert rep.queue_depth() == srv.queue_depth() == 3
        assert rep.health == "healthy"
        assert rep.stats["admissions"] == 0

    def test_staleness_walks_draining_then_dead_then_recovers(self, fleet):
        host, rep, _ = fleet["host_rep"](
            rep_kw={"draining_after_s": 0.15, "dead_after_s": 0.4})
        assert rep.health == "healthy"
        host.pause_heartbeats()
        time.sleep(0.25)
        assert rep.health == "draining"     # missed a few heartbeats
        time.sleep(0.3)
        assert rep.health == "dead"         # missed many
        host.resume_heartbeats()
        deadline = time.monotonic() + 5
        while rep.health != "healthy":
            assert time.monotonic() < deadline, "never recovered"
            time.sleep(0.01)

    def test_sketch_crosses_the_wire_for_affinity(self, fleet):
        from paddle_tpu.inference.prefix_cache import prefix_fingerprints
        _, rep, srv = fleet["host_rep"]()
        rep.start()
        p = np.arange(16, dtype=np.int32)   # two full pages to donate
        rid = rep.submit(np.concatenate([p, _prompt(1)]),
                         max_new_tokens=2)
        rep.wait(rid, timeout=20)
        deadline = time.monotonic() + 5
        fps = prefix_fingerprints(p, 8)
        while not all(fp in rep.prefix_sketch() for fp in fps):
            assert time.monotonic() < deadline, "sketch never arrived"
            time.sleep(0.01)


class TestRouterOverRemote:
    def test_affinity_routes_to_the_remote_holding_the_pages(self, fleet):
        reps = [fleet["host_rep"]()[1] for _ in range(3)]
        router = ReplicaRouter(reps)
        fleet["routers"].append(router)
        router.start(poll_interval=0.02)
        shared = np.arange(16, dtype=np.int32) % 16
        for i in range(5):
            p = np.concatenate([shared, _prompt(i + 1)])
            rid = router.submit(p, max_new_tokens=3)
            np.testing.assert_array_equal(router.wait(rid, timeout=30),
                                          stub_tokens(p, 3))
            # let the winner's donation reach the sketch before the
            # next submit routes (digest cadence 0.02s)
            time.sleep(0.08)
        assert router.stats["affinity_hits"] == 4
        assert router.stats["fallbacks"] == 1
        assert max(router.stats["routed"]) == 5

    def test_sigkill_less_crash_failover_bit_exact(self, fleet):
        """host.sever() is the in-process stand-in for a crash: the
        network face disappears, the supervisor detects it, and the
        synthesized evacuation requeues unstreamed requests bit-exact
        on the sibling while streamed ones flush partials."""
        host0, rep0, srv0 = fleet["host_rep"](
            rep_kw={"dead_after_s": 0.3})
        host1, rep1, srv1 = fleet["host_rep"]()
        router = ReplicaRouter([rep0, rep1], policy="least_loaded",
                               telemetry=True)
        fleet["routers"].append(router)
        router.start(poll_interval=0.02)
        rids = [(router.submit(_prompt(2, i + 1), max_new_tokens=4), i)
                for i in range(8)]
        time.sleep(0.02)
        host0.sever()
        outs = {}
        for rid, i in rids:
            outs[rid] = (router.wait(rid, timeout=30), _prompt(2, i + 1))
        full = partial = 0
        for rid, (got, p) in outs.items():
            exp = stub_tokens(p, 4)
            if np.array_equal(got, exp):
                full += 1
            else:
                np.testing.assert_array_equal(got, exp[:len(got)])
                partial += 1
        assert full + partial == 8
        assert router.stats["evacuations"] >= 1
        # the survivor leaked nothing
        free, live, pinned, cached = srv1.pool_balance()
        assert live == 0

    def test_mixed_local_and_remote_fleet_failover(self, fleet):
        """The tentpole contract: the router works UNCHANGED over a
        MIX of in-process server objects and remote processes — and a
        remote crash fails over onto the local sibling bit-exact."""
        _, remote, _ = fleet["host_rep"](rep_kw={"dead_after_s": 0.3})
        local = _server()
        fleet["servers"].append(local)
        router = ReplicaRouter([remote, local], policy="least_loaded")
        fleet["routers"].append(router)
        router.start(poll_interval=0.02)
        rids = [(router.submit(_prompt(4, i + 1), max_new_tokens=3), i)
                for i in range(6)]
        for rid, i in rids:
            np.testing.assert_array_equal(
                router.wait(rid, timeout=30),
                stub_tokens(_prompt(4, i + 1), 3))
        routed = router.stats["routed"]
        assert routed[0] > 0 and routed[1] > 0   # both kinds served
        # now crash the remote's network face with work queued on it
        fleet["hosts"][0].sever()
        more = [(router.submit(_prompt(6, i + 1), max_new_tokens=3), i)
                for i in range(4)]
        for rid, i in more:
            got = router.wait(rid, timeout=30)
            exp = stub_tokens(_prompt(6, i + 1), 3)
            np.testing.assert_array_equal(got, exp[:len(got)])
        assert router.health == "degraded"       # local still serving

    def test_rolling_restart_over_the_wire_zero_failures(self, fleet):
        reps = [fleet["host_rep"]()[1] for _ in range(2)]
        router = ReplicaRouter(reps, policy="least_loaded")
        fleet["routers"].append(router)
        router.start(poll_interval=0.02)
        rids = [(router.submit(_prompt(3, i + 1), max_new_tokens=4), i)
                for i in range(6)]
        router.rolling_restart(drain_timeout=60.0)
        for rid, i in rids:
            np.testing.assert_array_equal(
                router.wait(rid, timeout=30),
                stub_tokens(_prompt(3, i + 1), 4))
        assert router.stats["restarts"] == 2

    def test_fleet_page_merges_remote_snapshots(self, fleet):
        from paddle_tpu.telemetry import RouterTelemetry
        rt = RouterTelemetry()
        host, rep, srv = fleet["host_rep"](
            server_kw={"telemetry": True},
            rep_kw={"registry": rt.registry})
        router = ReplicaRouter([rep], telemetry=rt)
        fleet["routers"].append(router)
        router.start(poll_interval=0.02)
        rid = router.submit(_prompt(9, 1), max_new_tokens=3)
        router.wait(rid, timeout=30)
        page = router.fleet_metrics()
        # the remote server's registry crossed the wire into /fleet
        assert "serving_requests_total" in page
        # and the wire itself is accounted for on the client registry
        assert "net_frames_total" in page
        assert "net_call_seconds" in page
        assert "net_heartbeats_total" in page
        snap = router.fleet_snapshot()
        assert snap["serving_requests_total"]["samples"][
            ("finished",)] >= 1


class TestHostFuzz:
    """Satellite: a fuzzer hammering the host's port must never wedge
    a real client's call or kill the host loop."""

    def test_garbage_frames_do_not_kill_host_or_real_client(self, fleet):
        host, rep, _ = fleet["host_rep"]()
        rep.start()
        rng = random.Random(77)     # seeded-PRNG chaos pattern
        raw = socket.create_connection(host.address, timeout=5)
        try:
            for _ in range(30):
                kind = rng.randrange(3)
                if kind == 0:       # garbage payload, valid length
                    junk = bytes(rng.randrange(256)
                                 for _ in range(rng.randrange(1, 60)))
                    raw.sendall(struct.pack("!I", len(junk)) + junk)
                elif kind == 1:     # valid JSON, nonsense op
                    body = json.dumps({"id": rng.randrange(99),
                                       "op": "nope"}).encode()
                    raw.sendall(struct.pack("!I", len(body)) + body)
                else:               # valid JSON, not even a dict
                    body = json.dumps([1, 2, 3]).encode()
                    raw.sendall(struct.pack("!I", len(body)) + body)
            # a real client call still works mid-fuzz
            p = _prompt(8, 3)
            rid = rep.submit(p, max_new_tokens=4)
            np.testing.assert_array_equal(rep.wait(rid, timeout=20),
                                          stub_tokens(p, 4))
            # oversized length prefix severs ONLY the fuzzer's conn
            raw.sendall(struct.pack("!I", 0xFFFFFFFF) + b"xx")
            time.sleep(0.1)
            rid = rep.submit(p, max_new_tokens=2)
            np.testing.assert_array_equal(rep.wait(rid, timeout=20),
                                          stub_tokens(p, 2))
        finally:
            raw.close()

    def test_unknown_op_fails_that_call_typed(self, fleet):
        _, rep, _ = fleet["host_rep"]()
        with pytest.raises(ValueError, match="unknown wire op"):
            rep._call("definitely_not_an_op")
        assert rep.health == "healthy"      # connection survived
        assert rep._call("ping") == "pong"
