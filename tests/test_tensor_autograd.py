"""Tensor + eager autograd tape tests.

Modeled on the reference's OpTest numpy-oracle pattern
(python/paddle/fluid/tests/unittests/eager_op_test.py:313): outputs checked
against numpy, grads checked against analytic/numeric references.
"""
import numpy as np
import pytest

import paddle_tpu as pt


def test_to_tensor_roundtrip():
    x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])
    assert x.stop_gradient


def test_basic_arith_matches_numpy():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(3, 4).astype(np.float32)
    ta, tb = pt.to_tensor(a), pt.to_tensor(b)
    np.testing.assert_allclose((ta + tb).numpy(), a + b, rtol=1e-6)
    np.testing.assert_allclose((ta * tb).numpy(), a * b, rtol=1e-6)
    np.testing.assert_allclose((ta - tb).numpy(), a - b, rtol=1e-6)
    np.testing.assert_allclose((ta / (tb + 10)).numpy(), a / (b + 10),
                               rtol=1e-5)
    np.testing.assert_allclose((ta @ tb.T).numpy(), a @ b.T, rtol=1e-5)


def test_backward_simple():
    x = pt.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0], rtol=1e-6)


def test_backward_chain_and_accumulation():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    z1 = (y * y).sum()
    z1.backward()
    np.testing.assert_allclose(x.grad.numpy(), 9 * 2 * np.array([1.0, 2.0]),
                               rtol=1e-6)
    # second backward accumulates
    z2 = (x * 2.0).sum()
    z2.backward()
    np.testing.assert_allclose(
        x.grad.numpy(), 9 * 2 * np.array([1.0, 2.0]) + 2.0, rtol=1e-6)


def test_backward_through_shared_subexpr():
    x = pt.to_tensor(2.0, stop_gradient=False)
    y = x * x        # y = x^2
    z = y + y        # z = 2x^2 -> dz/dx = 4x = 8
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0, rtol=1e-6)


def test_matmul_grad_matches_numeric():
    a = np.random.randn(4, 5).astype(np.float32)
    b = np.random.randn(5, 3).astype(np.float32)
    ta = pt.to_tensor(a, stop_gradient=False)
    tb = pt.to_tensor(b, stop_gradient=False)
    loss = (ta @ tb).sum()
    loss.backward()
    np.testing.assert_allclose(ta.grad.numpy(), np.ones((4, 3)) @ b.T,
                               rtol=1e-5)
    np.testing.assert_allclose(tb.grad.numpy(), a.T @ np.ones((4, 3)),
                               rtol=1e-5)


def test_no_grad_blocks_tape():
    x = pt.to_tensor([1.0], stop_gradient=False)
    with pt.no_grad():
        y = x * 5.0
    assert y.stop_gradient
    assert y._node is None


def test_stop_gradient_cuts_graph():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2.0
    d = y.detach()
    z = (d * x).sum()
    z.backward()
    # d treated as constant: dz/dx = d = 2x
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0], rtol=1e-6)


def test_multi_output_op_grad():
    x = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                     stop_gradient=False)
    a, b, c = pt.ops.split(x, 3, axis=1)
    loss = (a * 1.0 + b * 2.0 + c * 3.0).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               np.tile([1.0, 2.0, 3.0], (2, 1)), rtol=1e-6)


def test_reductions_and_manip():
    a = np.random.randn(2, 3, 4).astype(np.float32)
    t = pt.to_tensor(a)
    np.testing.assert_allclose(t.sum(axis=1).numpy(), a.sum(1), rtol=1e-5)
    np.testing.assert_allclose(t.mean().numpy(), a.mean(), rtol=1e-5)
    np.testing.assert_allclose(t.reshape([6, 4]).numpy(), a.reshape(6, 4))
    np.testing.assert_allclose(t.transpose([2, 0, 1]).numpy(),
                               a.transpose(2, 0, 1))
    np.testing.assert_allclose(
        pt.ops.concat([t, t], axis=0).numpy(), np.concatenate([a, a], 0))


def test_indexing_and_grad():
    x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4),
                     stop_gradient=False)
    y = x[1]
    y.sum().backward()
    expected = np.zeros((3, 4), np.float32)
    expected[1] = 1.0
    np.testing.assert_allclose(x.grad.numpy(), expected)


def test_comparison_and_logical():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    b = pt.to_tensor([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a > b).numpy(), [False, False, True])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])


def test_pylayer_custom_backward():
    class Double(pt.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2.0

        @staticmethod
        def backward(ctx, grad):
            return grad * 100.0  # deliberately wrong to prove custom path

    x = pt.to_tensor([1.0], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [2.0])
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [100.0])


def test_autograd_grad_api():
    x = pt.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (g,) = pt.autograd.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [6.0], rtol=1e-6)


def test_random_reproducible():
    pt.seed(7)
    a = pt.ops.randn([4])
    pt.seed(7)
    b = pt.ops.randn([4])
    np.testing.assert_array_equal(a.numpy(), b.numpy())


def test_cast_astype():
    x = pt.to_tensor([1.5, 2.5])
    assert str(x.astype("int32").numpy().dtype) == "int32"
    assert x.astype(pt.bfloat16).dtype == pt.bfloat16


class TestRegisterHook:
    """Tensor.register_hook parity (reference eager/hooks.h TensorHook;
    python test: test_tensor_register_hook.py)."""

    def test_leaf_hook_scales_grad(self):
        x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
        x.register_hook(lambda g: g * 2)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 8.0], rtol=1e-6)

    def test_leaf_hook_called_once_with_accumulated_grad(self):
        calls = []
        x = pt.to_tensor([3.0], stop_gradient=False)
        x.register_hook(lambda g: calls.append(np.asarray(g.numpy())))
        y = x * x + x * 4.0   # two uses of x: dy/dx = 2x + 4 = 10
        y.backward()
        assert len(calls) == 1
        np.testing.assert_allclose(calls[0], [10.0], rtol=1e-6)
        np.testing.assert_allclose(x.grad.numpy(), [10.0], rtol=1e-6)

    def test_intermediate_hook_rewrites_cotangent(self):
        x = pt.to_tensor([2.0], stop_gradient=False)
        h = x * 3.0           # intermediate
        h.register_hook(lambda g: g * 10)
        y = h * h             # dy/dh = 2h = 12 -> hooked to 120 -> dx = 360
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [360.0], rtol=1e-6)

    def test_none_return_keeps_grad(self):
        seen = []
        x = pt.to_tensor([5.0], stop_gradient=False)
        x.register_hook(lambda g: seen.append(float(g.numpy()[0])))
        (x * 7.0).backward()
        assert seen == [7.0]
        np.testing.assert_allclose(x.grad.numpy(), [7.0], rtol=1e-6)

    def test_remove_handle(self):
        x = pt.to_tensor([1.0], stop_gradient=False)
        handle = x.register_hook(lambda g: g * 100)
        assert handle.remove()
        assert not handle.remove()   # idempotent
        (x * 2.0).backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0], rtol=1e-6)

    def test_stop_gradient_rejected(self):
        x = pt.to_tensor([1.0])
        with pytest.raises(RuntimeError):
            x.register_hook(lambda g: g)

    def test_no_phantom_hook_on_unreached_output(self):
        """Hooks fire only when gradient actually reaches the tensor
        (paddle semantics: no calls on zero-filled sibling cotangents)."""
        calls = []
        x = pt.to_tensor([1.0, 2.0, 3.0, 4.0], stop_gradient=False)
        a, b = pt.ops.split(x, 2)
        b.register_hook(lambda g: calls.append(1))
        a.sum().backward()
        assert calls == []
        np.testing.assert_allclose(x.grad.numpy(), [1, 1, 0, 0], rtol=1e-6)

    def test_hook_survives_inplace_rebind(self):
        """register_hook before an inplace op still fires after the op
        rebinds the tensor's tape node."""
        calls = []
        x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
        x.register_hook(lambda g: calls.append(1) or g * 3)
        x.add_(pt.to_tensor([1.0, 1.0]))
        x.sum().backward()
        assert calls == [1]

    def test_nonleaf_hook_survives_inplace_rebind(self):
        """A hook on a non-leaf tensor follows the tensor through an
        inplace op (fires on the post-mutation gradient)."""
        seen = []
        x = pt.to_tensor([2.0], stop_gradient=False)
        y = x * 2.0                  # non-leaf
        y.register_hook(lambda g: seen.append(float(g.numpy()[0])))
        y.add_(pt.to_tensor([1.0]))  # y = 2x + 1, rebinds y's node
        (y * 3.0).sum().backward()
        assert seen == [3.0]         # grad wrt post-mutation y
        np.testing.assert_allclose(x.grad.numpy(), [6.0], rtol=1e-6)
