"""Pipeline parallel tests: segmentation, local schedule parity, SPMD GPipe.

Mirrors reference tests hybrid_parallel_pp_transformer.py (loss parity
between pipelined and dense execution).
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.pipeline import (
    LayerDesc, LocalPipelineRunner, PipelineLayer, SegmentLayers,
)
from paddle_tpu.parallel.pp_schedule import (
    pipeline_train_step, spmd_pipeline_forward, stack_stage_params,
)
from paddle_tpu.parallel.mesh import P


def test_segment_layers_uniform():
    segs = SegmentLayers([None] * 10, num_parts=4).do_segment()
    assert segs == [0, 3, 6, 8, 10]
    assert SegmentLayers.uniform(8, 4) == [0, 2, 4, 6, 8]


class _Block(nn.Layer):
    def __init__(self, width=8):
        super().__init__()
        self.fc = nn.Linear(width, width)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return x + F.tanh(self.fc(x))


def test_pipeline_layer_builds_stages():
    pipe = PipelineLayer([LayerDesc(_Block, 8) for _ in range(6)],
                         num_stages=3)
    assert len(pipe.stages) == 3
    assert len(pipe.stages[0]) == 2
    x = pt.to_tensor(np.random.randn(2, 8).astype(np.float32))
    out = pipe(x)
    assert out.shape == [2, 8]


def test_local_pipeline_runner_matches_full_batch():
    pt.seed(3)
    loss_fn = lambda out, y: ((out - y) ** 2).mean()  # noqa: E731
    pipe = PipelineLayer([LayerDesc(_Block, 8) for _ in range(4)],
                         num_stages=2, loss_fn=loss_fn)
    opt = pt.optimizer.SGD(learning_rate=0.0,
                           parameters=pipe.parameters())
    runner = LocalPipelineRunner(pipe, opt)
    x = np.random.randn(4, 8).astype(np.float32)
    y = np.random.randn(4, 8).astype(np.float32)
    avg_loss = runner.train_batch(x, y, num_microbatches=2)
    full = float(loss_fn(pipe(pt.to_tensor(x)), pt.to_tensor(y)).numpy())
    # microbatch-mean of MSE == full-batch MSE for equal splits
    np.testing.assert_allclose(avg_loss, full, rtol=1e-5)


def test_spmd_pipeline_forward_matches_sequential():
    """The scan+ppermute wave must equal running stages sequentially."""
    pt.seed(11)
    S = 4
    pipe = PipelineLayer([LayerDesc(_Block, 16) for _ in range(S)],
                         num_stages=S)
    mesh = dist.init_mesh(dp=1, pp=S, mp=1)
    stacked, template = stack_stage_params(pipe)
    from paddle_tpu.jit import functional_call

    def stage_fn(params_one, x):
        return functional_call(template, params_one, x)

    M, mb, d = 3, 2, 16
    x_micro = np.random.randn(M, mb, d).astype(np.float32)

    def body(stk, xm):
        return spmd_pipeline_forward(stage_fn, stk, xm, S)

    outs = jax.shard_map(
        body, mesh=mesh.mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked), P()),
        out_specs=P(), check_vma=False,
    )(stacked, jnp.asarray(x_micro))

    # sequential reference
    ref = []
    for m in range(M):
        h = pt.to_tensor(x_micro[m])
        h = pipe(h)
        ref.append(h.numpy())
    ref = np.stack(ref)
    np.testing.assert_allclose(np.asarray(outs), ref, rtol=2e-4, atol=2e-5)


def test_pipeline_train_step_loss_decreases():
    pt.seed(1)
    S = 2
    width = 16
    pipe = PipelineLayer([LayerDesc(_Block, width) for _ in range(S * 2)],
                         num_stages=S)
    mesh = dist.init_mesh(dp=1, pp=S, mp=1)
    opt = pt.optimizer.AdamW(learning_rate=5e-3,
                             parameters=pipe.parameters())

    w_out = np.random.randn(width, 4).astype(np.float32) * 0.1

    def embed_fn(extra, ids):
        return ids  # identity embedding: inputs are already features

    def head_loss_fn(extra, hidden, labels):
        logits = hidden @ w_out
        return jnp.mean((logits - labels) ** 2)

    with mesh:
        step, stacked, extra, states = pipeline_train_step(
            pipe, embed_fn, head_loss_fn, opt, mesh, num_micro=2,
            remat=False)
        x = np.random.randn(4, width).astype(np.float32)
        y = np.random.randn(4, 4).astype(np.float32)
        losses = []
        for i in range(12):
            loss, stacked, extra, states = step(stacked, extra, states,
                                                jnp.asarray(x),
                                                jnp.asarray(y), i + 1)
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses
