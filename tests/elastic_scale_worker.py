"""Worker for the elastic SCALE-IN e2e (VERDICT r4 #6): the full
reference elastic story composed with TPU-native re-mesh restart.

Phase "train": 2 nodes under ElasticManager (TCPStore heartbeats +
endpoint registry). Node 0 trains the HYBRID pipeline (tp2 x pp2 x
sharding2 on the 8-device virtual mesh) and checkpoints the canonical
per-layer layout (params + Adam moments) every step; node 1 crashes.
Node 0's manager detects the lost heartbeat, records the scale plan
(surviving endpoints), and exits asking for a restart.

Phase "resume": the relaunched single node rewrites its env from the
plan (reference manager.py:469-604 endpoint rewrite), restores the
checkpoint ONTO A DIFFERENT PIPELINE LAYOUT (pp4 x mp2) via the
converter's restack helpers, and finishes training.
"""
import json
import os
import pickle
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.elastic import ElasticManager
from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                        init_llama_tp_params,
                                        make_llama_tp_fns, restack_blocks,
                                        unstack_blocks)

RANK = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
WORLD = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
CKDIR = os.environ["CKPT_DIR"]
PHASE = os.environ.get("PHASE", "train")
CRASH_RANK = int(os.environ.get("CRASH_RANK", "-1"))
CRASH_STEP = int(os.environ.get("CRASH_STEP", "2"))
TOTAL = int(os.environ.get("TOTAL_STEPS", "5"))
MASTER = os.environ.get("ELASTIC_MASTER", "127.0.0.1:29741")

NH, L, H, F, V = 4, 4, 16, 32, 64
RESTART_RC = 31


def step_ids(i):
    return jnp.asarray(np.random.RandomState(1000 + i)
                       .randint(0, V, size=(8, 8)).astype(np.int32))


def build(mesh, blocks):
    fns, specs = make_llama_tp_fns(NH, 2)
    opt = pt.optimizer.AdamW(learning_rate=1e-2)
    embed, head = build.embed, build.head
    return build_hybrid_train_step(
        *fns, blocks, embed, head, mesh, opt, num_micro=2,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=1, donate=False)


def save_canonical(params, opt_state, step, pp_degree):
    canon = {
        "blocks": unstack_blocks(params["blocks"], L, pp_degree=pp_degree),
        "embed": {k: np.asarray(v) for k, v in params["embed"].items()},
        "head": {k: np.asarray(v) for k, v in params["head"].items()},
        "m_blocks": unstack_blocks(opt_state["m"]["blocks"], L,
                                   pp_degree=pp_degree),
        "v_blocks": unstack_blocks(opt_state["v"]["blocks"], L,
                                   pp_degree=pp_degree),
        "m_embed": {k: np.asarray(v)
                    for k, v in opt_state["m"]["embed"].items()},
        "v_embed": {k: np.asarray(v)
                    for k, v in opt_state["v"]["embed"].items()},
        "m_head": {k: np.asarray(v)
                   for k, v in opt_state["m"]["head"].items()},
        "v_head": {k: np.asarray(v)
                   for k, v in opt_state["v"]["head"].items()},
        "step": step,
    }
    with open(os.path.join(CKDIR, f"hybrid_{step}.pkl"), "wb") as f:
        pickle.dump(canon, f)
    with open(os.path.join(CKDIR, "LATEST"), "w") as f:
        f.write(str(step))


def main_train():
    from paddle_tpu.runtime import TCPStore
    host, port = MASTER.rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), is_master=(RANK == 0),
                     world_size=WORLD)
    # build BEFORE registering: on a contended box the jit compile can
    # starve the heartbeat thread for seconds, and a short timeout
    # would false-trigger a restart on a perfectly healthy node
    if RANK == 0:
        blocks, embed, head = init_llama_tp_params(
            L, H, F, V, rng=np.random.RandomState(77))
        build.embed, build.head = embed, head
        mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
        step_fn, params, opt_state, _sh = build(mesh, blocks)
        # warm the compile BEFORE registering: in-loop steps stay fast
        # so the heartbeat/detection timing below is meaningful
        warm = step_ids(0)
        step_fn(params, opt_state, warm, warm, 1)
    mgr = ElasticManager(store=store, node_id=str(RANK), np=WORLD,
                         heartbeat_interval=0.3, heartbeat_timeout=8.0,
                         job_id="scale-e2e")
    mgr.register()
    mgr.publish_endpoint(f"127.0.0.1:{9400 + RANK}")
    mgr.wait_for_np(WORLD, timeout=600)
    losses = []
    for i in range(1, TOTAL + 1):
        # lockstep barrier WITH failure detection: a missing peer stops
        # heartbeating and the manager asks for a restart
        store.add(f"sbar/{i}", 1)
        deadline = time.time() + 60
        while store.add(f"sbar/{i}", 0) < WORLD:
            if mgr.should_restart():
                if RANK == 0:
                    plan_np, plan_eps = mgr.scale_plan()
                    with open(os.path.join(CKDIR, "PLAN.json"), "w") as f:
                        json.dump({"np": plan_np, "endpoints": plan_eps,
                                   "losses": losses}, f)
                mgr.exit(completed=False)
                return RESTART_RC
            if time.time() > deadline:
                raise RuntimeError(f"barrier timeout at step {i}")
            time.sleep(0.02)
        if RANK == CRASH_RANK and i == CRASH_STEP:
            # GRACEFUL departure (preemption/scale-in): exit 0 so the
            # launcher keeps the survivors running and node 0's manager
            # does the detecting — the hard-crash story is covered by
            # the kill-relaunch e2e (test_checkpoint_converter)
            mgr.exit(completed=True)
            os._exit(0)
        if RANK == 0:
            loss, params, opt_state = step_fn(params, opt_state,
                                              step_ids(i), step_ids(i), i)
            losses.append(float(loss))
            save_canonical(params, opt_state, i, pp_degree=2)
    mgr.exit(completed=True)
    return 0


def main_resume():
    from paddle_tpu.runtime import TCPStore
    host, port = os.environ["RESUME_MASTER"].rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), is_master=True,
                     world_size=1)
    mgr = ElasticManager(store=store, node_id="0", np=1,
                         heartbeat_interval=0.3, heartbeat_timeout=8.0,
                         job_id="scale-e2e")
    mgr.register()
    mgr.publish_endpoint("127.0.0.1:9400")
    # endpoint/np rewrite for the shrunk membership (reference
    # manager.py:469-604) — the new env drives the rebuilt mesh
    plan = json.load(open(os.path.join(CKDIR, "PLAN.json")))
    env = mgr.rewrite_env(mgr.endpoints())
    assert env["PADDLE_TRAINERS_NUM"] == str(plan["np"]) == "1", env
    assert env["PADDLE_TRAINER_ID"] == "0", env

    last = int(open(os.path.join(CKDIR, "LATEST")).read())
    with open(os.path.join(CKDIR, f"hybrid_{last}.pkl"), "rb") as f:
        canon = pickle.load(f)
    build.embed = {k: jnp.asarray(v) for k, v in canon["embed"].items()}
    build.head = {k: jnp.asarray(v) for k, v in canon["head"].items()}
    # DIFFERENT pipeline layout than the checkpoint was trained on
    mesh4 = dist.init_mesh(dp=1, pp=4, sharding=1, mp=2)
    step_fn, params, opt_state, _sh = build(mesh4, canon["blocks"])
    # Adam moments restack onto the new pp exactly like the params
    for key, mk, ek, hk in (("m", "m_blocks", "m_embed", "m_head"),
                            ("v", "v_blocks", "v_embed", "v_head")):
        stacked = restack_blocks(canon[mk], mesh4)
        new = {"blocks": stacked,
               "embed": {k: jnp.asarray(v) for k, v in canon[ek].items()},
               "head": {k: jnp.asarray(v) for k, v in canon[hk].items()}}
        opt_state[key] = jax.tree_util.tree_map(
            lambda cur, val: jax.device_put(jnp.asarray(val),
                                            cur.sharding),
            opt_state[key], new)
    losses = []
    for i in range(last + 1, TOTAL + 1):
        loss, params, opt_state = step_fn(params, opt_state,
                                          step_ids(i), step_ids(i), i)
        losses.append(float(loss))
    with open(os.path.join(CKDIR, "result.json"), "w") as f:
        json.dump({"resumed_from": last, "losses": losses,
                   "train_losses": plan["losses"]}, f)
    mgr.exit(completed=True)
    return 0


if __name__ == "__main__":
    sys.exit(main_train() if PHASE == "train" else main_resume())
