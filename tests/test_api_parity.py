"""Namespace-level API parity vs the reference's public __all__ lists.

Parses /root/reference/python/paddle/*'s __all__ (no reference import) and
asserts our namespaces expose the same names, modulo an explicit,
documented allowlist. Skips when the reference tree is absent.
"""
import ast
import os

import pytest

import paddle_tpu as pt

R = "/root/reference/python/paddle"

pytestmark = pytest.mark.skipif(not os.path.isdir(R),
                                reason="reference tree not mounted")

# names we intentionally do not provide (documented divergences)
ALLOWED_MISSING = {
    "paddle (top)": {
        # device-specific / framework-internal surface with no TPU meaning
        "XPUPlace", "IPUPlace", "MLUPlace", "CustomPlace",
        "is_compiled_with_cinn", "is_compiled_with_ipu",
        "is_compiled_with_npu", "is_compiled_with_mlu",
        "is_compiled_with_rocm", "version", "fluid", "monkey_patch_variable",
        "monkey_patch_math_varbase", "enable_autograd",
    },
    "paddle.nn.functional": set(),
    "paddle.nn": set(),
    "paddle.distributed": set(),
    "paddle.vision.transforms": set(),
    "paddle.vision.models": set(),
    "paddle.io": set(),
    "paddle.distribution": set(),
    "paddle.incubate": set(),
    "paddle.optimizer": set(),
    "paddle.metric": set(),
}


def ref_all(path):
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    try:
                        return [ast.literal_eval(e) for e in node.value.elts]
                    except Exception:
                        return []
    return []


def _mod(name):
    import importlib
    return importlib.import_module(name)


CASES = [
    ("paddle (top)", f"{R}/__init__.py", lambda: pt),
    ("paddle.static", f"{R}/static/__init__.py",
     lambda: _mod("paddle_tpu.static")),
    ("paddle.static.nn", f"{R}/static/nn/__init__.py",
     lambda: _mod("paddle_tpu.static.nn")),
    ("paddle.jit", f"{R}/jit/__init__.py", lambda: _mod("paddle_tpu.jit")),
    ("paddle.amp", f"{R}/amp/__init__.py", lambda: _mod("paddle_tpu.amp")),
    ("paddle.linalg", f"{R}/linalg.py", lambda: pt.linalg),
    ("paddle.fft", f"{R}/fft.py", lambda: pt.fft),
    ("paddle.sparse", f"{R}/sparse/__init__.py", lambda: pt.sparse),
    ("paddle.text", f"{R}/text/__init__.py", lambda: pt.text),
    ("paddle.audio", f"{R}/audio/__init__.py", lambda: pt.audio),
    ("paddle.autograd", f"{R}/autograd/__init__.py",
     lambda: _mod("paddle_tpu.autograd")),
    ("paddle.utils", f"{R}/utils/__init__.py",
     lambda: _mod("paddle_tpu.utils")),
    ("paddle.geometric", f"{R}/geometric/__init__.py",
     lambda: pt.geometric),
    ("paddle.quantization", f"{R}/quantization/__init__.py",
     lambda: pt.quantization),
    ("paddle.distributed.fleet", f"{R}/distributed/fleet/__init__.py",
     lambda: pt.distributed.fleet),
    ("paddle.nn.initializer", f"{R}/nn/initializer/__init__.py",
     lambda: pt.nn.initializer),
    ("paddle.nn.utils", f"{R}/nn/utils/__init__.py", lambda: pt.nn.utils),
    ("paddle.vision.ops", f"{R}/vision/ops.py", lambda: pt.vision.ops),
    ("paddle.vision.datasets", f"{R}/vision/datasets/__init__.py",
     lambda: pt.vision.datasets),
    ("paddle.profiler", f"{R}/profiler/__init__.py", lambda: pt.profiler),
    ("paddle.device", f"{R}/device/__init__.py", lambda: pt.device),
    ("paddle.optimizer.lr", f"{R}/optimizer/lr.py",
     lambda: pt.optimizer.lr),
    ("paddle.incubate.nn", f"{R}/incubate/nn/__init__.py",
     lambda: _mod("paddle_tpu.incubate.nn")),
    ("paddle.incubate.nn.functional",
     f"{R}/incubate/nn/functional/__init__.py",
     lambda: _mod("paddle_tpu.incubate.nn.functional")),
    ("paddle.incubate.autograd", f"{R}/incubate/autograd/__init__.py",
     lambda: _mod("paddle_tpu.incubate.autograd")),
    ("paddle.distributed.fleet.utils",
     f"{R}/distributed/fleet/utils/__init__.py",
     lambda: pt.distributed.fleet.utils),
    ("paddle.nn.quant", f"{R}/nn/quant/__init__.py",
     lambda: _mod("paddle_tpu.nn.quant")),
    ("paddle.distribution.transform", f"{R}/distribution/transform.py",
     lambda: pt.distribution.transform),
    ("paddle.nn", f"{R}/nn/__init__.py", lambda: _mod("paddle_tpu.nn")),
    ("paddle.nn.functional", f"{R}/nn/functional/__init__.py",
     lambda: _mod("paddle_tpu.nn.functional")),
    ("paddle.distributed", f"{R}/distributed/__init__.py",
     lambda: pt.distributed),
    ("paddle.vision.transforms", f"{R}/vision/transforms/__init__.py",
     lambda: pt.vision.transforms),
    ("paddle.vision.models", f"{R}/vision/models/__init__.py",
     lambda: pt.vision.models),
    ("paddle.io", f"{R}/io/__init__.py", lambda: pt.io),
    ("paddle.distribution", f"{R}/distribution/__init__.py",
     lambda: pt.distribution),
    ("paddle.incubate", f"{R}/incubate/__init__.py", lambda: pt.incubate),
    ("paddle.optimizer", f"{R}/optimizer/__init__.py",
     lambda: pt.optimizer),
    ("paddle.metric", f"{R}/metric/__init__.py", lambda: pt.metric),
]


@pytest.mark.parametrize("name,path,get_mod",
                         CASES, ids=[c[0] for c in CASES])
def test_namespace_parity(name, path, get_mod):
    want = ref_all(path)
    if not want:
        pytest.skip("no __all__ in reference module")
    mod = get_mod()
    allowed = ALLOWED_MISSING.get(name, set())
    missing = [w for w in want
               if not hasattr(mod, w) and w not in allowed]
    assert not missing, f"{name} missing {len(missing)}: {missing}"


def test_tensor_method_parity():
    """Every name in the reference's tensor_method_func monkey-patch list
    resolves on our Tensor."""
    path = f"{R}/tensor/__init__.py"
    tree = ast.parse(open(path).read())
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "tensor_method_func":
                    names = [ast.literal_eval(e) for e in node.value.elts]
    assert names, "reference list not found"
    x = pt.to_tensor([1.0])
    missing = [n for n in names if not hasattr(x, n)]
    assert not missing, f"Tensor missing {len(missing)}: {missing}"
