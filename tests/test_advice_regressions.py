"""Regression tests for the round-1 advisor findings (ADVICE.md):
(a) dy2static visit_If UnboundLocalError for names first bound in a branch,
(b) dy2static closure cache keyed only by __code__,
(c) quantization configs keyed by id(layer) lost across deepcopy,
(d) RPC cookie derivable from a pre-shared secret (never transits store),
(e) static gradients() dropping ops when any output is a wrt var.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.jit.dy2static import convert_to_static, UNDEFINED


# ---------------------------------------------------------------- (a)

class TestBranchFirstBinding:
    def test_var_first_bound_in_branch_eager(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2
            else:
                y = x * 3
            return y

        static = convert_to_static(f)
        out = static(np.array([1.0, 2.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
        out = static(np.array([-1.0, -2.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [-3.0, -6.0])

    def test_var_first_bound_in_branch_traced(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2
            else:
                y = x * 3
            return y

        static = convert_to_static(f)
        out = jax.jit(static)(jnp.array([1.0, 2.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])

    def test_one_sided_binding_unused_is_ok_eager(self):
        # `tmp` only exists on the positive path and is only used there;
        # eager execution of the negative path must not crash
        def f(x):
            if x.sum() > 0:
                tmp = x * 10
                out = tmp + 1
            else:
                out = x - 1
            return out

        static = convert_to_static(f)
        out = static(np.array([-1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [-2.0])
        out = static(np.array([1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [11.0])

    def test_undefined_use_raises_clearly(self):
        with pytest.raises(Exception):
            UNDEFINED + 1

    def test_loop_first_binding(self):
        def f(x):
            for i in range(3):
                acc = x * i if i == 0 else acc + x * i
            return acc

        # acc first bound inside the loop; eager path must work
        static = convert_to_static(f)
        out = static(np.array([1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [3.0])


# ---------------------------------------------------------------- (b)

class TestClosureCache:
    def test_factory_closures_not_conflated(self):
        def make(scale):
            def f(x):
                if x.sum() > 0:
                    y = x * scale
                else:
                    y = x
                return y
            return f

        f2 = convert_to_static(make(2.0))
        f5 = convert_to_static(make(5.0))
        x = np.array([1.0], np.float32)
        np.testing.assert_allclose(np.asarray(f2(x)), [2.0])
        np.testing.assert_allclose(np.asarray(f5(x)), [5.0])

    def test_nonlocal_rebind_stays_live(self):
        # cells are bound, not baked: a rebind after conversion must be
        # seen by the converted function, like the original would
        def make():
            s = 2.0

            def f(x):
                if x.sum() > 0:
                    y = x * s
                else:
                    y = x
                return y

            def set_s(v):
                nonlocal s
                s = v
            return f, set_s

        f, set_s = make()
        static = convert_to_static(f)
        x = np.array([1.0], np.float32)
        np.testing.assert_allclose(np.asarray(static(x)), [2.0])
        set_s(7.0)
        np.testing.assert_allclose(np.asarray(static(x)), [7.0])

    def test_fn_memo_bounded(self):
        from paddle_tpu.jit import dy2static as d

        def make(k):
            def f(x):
                if x.sum() > 0:
                    y = x + k
                else:
                    y = x
                return y
            return f

        for i in range(int(d._FN_MEMO_MAX * 1.5)):
            convert_to_static(make(float(i)))
        assert len(d._fn_memo) <= d._FN_MEMO_MAX


class TestUndefinedGuards:
    def test_comparison_raises(self):
        with pytest.raises(Exception):
            UNDEFINED == 0

    def test_float_raises(self):
        with pytest.raises(Exception):
            float(UNDEFINED)

    def test_returning_one_sided_var_fails_on_use(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2
            return y

        static = convert_to_static(f)
        out = static(np.array([-1.0], np.float32))
        # using the escaped placeholder must raise, not silently compare
        with pytest.raises(Exception):
            out + 1


# ---------------------------------------------------------------- (c)

class TestQuantConfigKeying:
    def test_layer_config_survives_deepcopy(self):
        from paddle_tpu.quantization import (QAT, QuantConfig,
                                             FakeQuanterWithAbsMaxObserver)
        net = pt.nn.Sequential(pt.nn.Linear(4, 4), pt.nn.Linear(4, 2))
        target = net[0]
        q = FakeQuanterWithAbsMaxObserver()
        cfg = QuantConfig()
        cfg.add_layer_config(target, activation=q, weight=q)
        qat = QAT(cfg)
        qmodel = qat.quantize(net)  # deepcopies internally
        from paddle_tpu.quantization import QuantedLinear
        subs = dict(qmodel.named_sublayers())
        assert any(isinstance(s, QuantedLinear) for s in subs.values()), \
            "per-layer config was lost across deepcopy"
        # the second Linear had no config and must remain unquantized
        n_quanted = sum(isinstance(s, QuantedLinear) for s in subs.values())
        assert n_quanted == 1

    def test_full_name_stable_across_deepcopy(self):
        import copy
        l = pt.nn.Linear(3, 3)
        assert copy.deepcopy(l).full_name() == l.full_name()


# ---------------------------------------------------------------- (d)

class TestRpcCookie:
    def test_secret_derivation_deterministic_and_store_free(self):
        import hashlib
        import hmac as hmac_mod
        # the derivation used by init_rpc when PADDLE_RPC_SECRET is set:
        # purely local, so equal secrets -> equal cookies on every rank
        d1 = hmac_mod.new(b"s3cret", b"paddle_tpu/rpc/cookie/v1",
                          hashlib.sha256).digest()
        d2 = hmac_mod.new(b"s3cret", b"paddle_tpu/rpc/cookie/v1",
                          hashlib.sha256).digest()
        d3 = hmac_mod.new(b"other", b"paddle_tpu/rpc/cookie/v1",
                          hashlib.sha256).digest()
        assert d1 == d2 != d3

    def test_init_rpc_honors_secret_env(self, monkeypatch):
        import inspect
        from paddle_tpu.parallel import rpc as rpc_mod
        src = inspect.getsource(rpc_mod.init_rpc)
        assert "PADDLE_RPC_SECRET" in src


# ---------------------------------------------------------------- (e)

class TestGradientsSiblingOutputs:
    def test_grad_wrt_one_output_of_multi_output_op(self):
        """An op producing (a, b) where only `a` is a wrt var: grads of a
        target that consumes BOTH must not lose `b`'s op."""
        import paddle_tpu.static as static
        from paddle_tpu.core.tensor import dispatch

        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", shape=[3], dtype="float32")
            a, b = dispatch(lambda v: (v * 2.0, v * 3.0), x, name="twin")
            loss = (a + b).sum()
            gvars = static.gradients([loss], [a])

        exe = static.Executor()
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        out = exe.run(prog, feed={"x": xv}, fetch_list=[gvars[0]])
        # d loss / d a = 1 everywhere; before the fix the op producing
        # (a, b) was dropped entirely, so sibling b was missing and the
        # replay crashed (or produced wrong grads)
        np.testing.assert_allclose(out[0], np.ones(3, np.float32))
