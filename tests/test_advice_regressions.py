"""Regression tests for the round-1 advisor findings (ADVICE.md):
(a) dy2static visit_If UnboundLocalError for names first bound in a branch,
(b) dy2static closure cache keyed only by __code__,
(c) quantization configs keyed by id(layer) lost across deepcopy,
(d) RPC cookie derivable from a pre-shared secret (never transits store),
(e) static gradients() dropping ops when any output is a wrt var.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.jit.dy2static import convert_to_static, UNDEFINED


# ---------------------------------------------------------------- (a)

class TestBranchFirstBinding:
    def test_var_first_bound_in_branch_eager(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2
            else:
                y = x * 3
            return y

        static = convert_to_static(f)
        out = static(np.array([1.0, 2.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
        out = static(np.array([-1.0, -2.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [-3.0, -6.0])

    def test_var_first_bound_in_branch_traced(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2
            else:
                y = x * 3
            return y

        static = convert_to_static(f)
        out = jax.jit(static)(jnp.array([1.0, 2.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])

    def test_one_sided_binding_unused_is_ok_eager(self):
        # `tmp` only exists on the positive path and is only used there;
        # eager execution of the negative path must not crash
        def f(x):
            if x.sum() > 0:
                tmp = x * 10
                out = tmp + 1
            else:
                out = x - 1
            return out

        static = convert_to_static(f)
        out = static(np.array([-1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [-2.0])
        out = static(np.array([1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [11.0])

    def test_undefined_use_raises_clearly(self):
        with pytest.raises(Exception):
            UNDEFINED + 1

    def test_loop_first_binding(self):
        def f(x):
            for i in range(3):
                acc = x * i if i == 0 else acc + x * i
            return acc

        # acc first bound inside the loop; eager path must work
        static = convert_to_static(f)
        out = static(np.array([1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [3.0])


# ---------------------------------------------------------------- (b)

class TestClosureCache:
    def test_factory_closures_not_conflated(self):
        def make(scale):
            def f(x):
                if x.sum() > 0:
                    y = x * scale
                else:
                    y = x
                return y
            return f

        f2 = convert_to_static(make(2.0))
        f5 = convert_to_static(make(5.0))
        x = np.array([1.0], np.float32)
        np.testing.assert_allclose(np.asarray(f2(x)), [2.0])
        np.testing.assert_allclose(np.asarray(f5(x)), [5.0])

    def test_nonlocal_rebind_stays_live(self):
        # cells are bound, not baked: a rebind after conversion must be
        # seen by the converted function, like the original would
        def make():
            s = 2.0

            def f(x):
                if x.sum() > 0:
                    y = x * s
                else:
                    y = x
                return y

            def set_s(v):
                nonlocal s
                s = v
            return f, set_s

        f, set_s = make()
        static = convert_to_static(f)
        x = np.array([1.0], np.float32)
        np.testing.assert_allclose(np.asarray(static(x)), [2.0])
        set_s(7.0)
        np.testing.assert_allclose(np.asarray(static(x)), [7.0])

    def test_fn_memo_bounded(self):
        from paddle_tpu.jit import dy2static as d

        def make(k):
            def f(x):
                if x.sum() > 0:
                    y = x + k
                else:
                    y = x
                return y
            return f

        for i in range(int(d._FN_MEMO_MAX * 1.5)):
            convert_to_static(make(float(i)))
        assert len(d._fn_memo) <= d._FN_MEMO_MAX


class TestUndefinedGuards:
    def test_comparison_raises(self):
        with pytest.raises(Exception):
            UNDEFINED == 0

    def test_float_raises(self):
        with pytest.raises(Exception):
            float(UNDEFINED)

    def test_returning_one_sided_var_fails_on_use(self):
        def f(x):
            if x.sum() > 0:
                y = x * 2
            return y

        static = convert_to_static(f)
        out = static(np.array([-1.0], np.float32))
        # using the escaped placeholder must raise, not silently compare
        with pytest.raises(Exception):
            out + 1


# ---------------------------------------------------------------- (c)

class TestQuantConfigKeying:
    def test_layer_config_survives_deepcopy(self):
        from paddle_tpu.quantization import (QAT, QuantConfig,
                                             FakeQuanterWithAbsMaxObserver)
        net = pt.nn.Sequential(pt.nn.Linear(4, 4), pt.nn.Linear(4, 2))
        target = net[0]
        q = FakeQuanterWithAbsMaxObserver()
        cfg = QuantConfig()
        cfg.add_layer_config(target, activation=q, weight=q)
        qat = QAT(cfg)
        qmodel = qat.quantize(net)  # deepcopies internally
        from paddle_tpu.quantization import QuantedLinear
        subs = dict(qmodel.named_sublayers())
        assert any(isinstance(s, QuantedLinear) for s in subs.values()), \
            "per-layer config was lost across deepcopy"
        # the second Linear had no config and must remain unquantized
        n_quanted = sum(isinstance(s, QuantedLinear) for s in subs.values())
        assert n_quanted == 1

    def test_full_name_stable_across_deepcopy(self):
        import copy
        l = pt.nn.Linear(3, 3)
        assert copy.deepcopy(l).full_name() == l.full_name()


# ---------------------------------------------------------------- (d)

class TestRpcCookie:
    def test_secret_derivation_deterministic_and_store_free(self):
        import hashlib
        import hmac as hmac_mod
        # the derivation used by init_rpc when PADDLE_RPC_SECRET is set:
        # purely local, so equal secrets -> equal cookies on every rank
        d1 = hmac_mod.new(b"s3cret", b"paddle_tpu/rpc/cookie/v1",
                          hashlib.sha256).digest()
        d2 = hmac_mod.new(b"s3cret", b"paddle_tpu/rpc/cookie/v1",
                          hashlib.sha256).digest()
        d3 = hmac_mod.new(b"other", b"paddle_tpu/rpc/cookie/v1",
                          hashlib.sha256).digest()
        assert d1 == d2 != d3

    def test_init_rpc_honors_secret_env(self, monkeypatch):
        import inspect
        from paddle_tpu.parallel import rpc as rpc_mod
        src = inspect.getsource(rpc_mod.init_rpc)
        assert "PADDLE_RPC_SECRET" in src


# ---------------------------------------------------------------- (e)

class TestGradientsSiblingOutputs:
    def test_grad_wrt_one_output_of_multi_output_op(self):
        """An op producing (a, b) where only `a` is a wrt var: grads of a
        target that consumes BOTH must not lose `b`'s op."""
        import paddle_tpu.static as static
        from paddle_tpu.core.tensor import dispatch

        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", shape=[3], dtype="float32")
            a, b = dispatch(lambda v: (v * 2.0, v * 3.0), x, name="twin")
            loss = (a + b).sum()
            gvars = static.gradients([loss], [a])

        exe = static.Executor()
        xv = np.array([1.0, 2.0, 3.0], np.float32)
        out = exe.run(prog, feed={"x": xv}, fetch_list=[gvars[0]])
        # d loss / d a = 1 everywhere; before the fix the op producing
        # (a, b) was dropped entirely, so sibling b was missing and the
        # replay crashed (or produced wrong grads)
        np.testing.assert_allclose(out[0], np.ones(3, np.float32))


# ---------------------------------------------------------------- (f) r4

class TestTiedHeadMpGuard:
    def test_full_table_fns_refused_on_mp2_mesh(self):
        """tie_embed_head + mp>1 must refuse any embed/head pair not
        marked _mp_aware: a full-table lookup fn (e.g. a model
        pipeline_decompose) would silently read the [V/mp, h] slice and
        train to NaN."""
        import paddle_tpu.parallel as dist
        from paddle_tpu.parallel.pp_1f1b import (build_1f1b_train_step,
                                                 make_tied_lm_fns)
        mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
        rng = np.random.RandomState(0)
        blocks = [{"w": jnp.asarray(rng.randn(16, 16).astype(np.float32))}
                  for _ in range(4)]
        embed = {"table": jnp.asarray(
            rng.randn(64, 16).astype(np.float32))}
        embed_fn, head_loss_fn = make_tied_lm_fns()
        with pytest.raises(ValueError, match="_mp_aware"):
            build_1f1b_train_step(
                lambda p, x: jnp.tanh(x @ p["w"]), embed_fn, head_loss_fn,
                blocks, embed, {}, mesh, num_micro=2, tie_embed_head=True)

    def test_mp_aware_factories_carry_marker(self):
        from paddle_tpu.parallel.hybrid import (make_llama_tp_fns,
                                                make_tied_tp_lm_fns)
        (_b, e1, h1), _ = make_llama_tp_fns(4, 2)
        assert e1._mp_aware and h1._mp_aware
        (_b2, e2, h2), _ = make_tied_tp_lm_fns(4, 2)
        assert e2._mp_aware and h2._mp_aware


class TestPartialOpsDivisibility:
    def test_partial_allgather_rejects_indivisible(self):
        import paddle_tpu.parallel as dist
        from paddle_tpu.parallel.mesh import P as Pspec
        mesh = dist.init_mesh(dp=4)

        def body(x):
            return dist.collective.partial_allgather(x, group="dp")

        bad = jnp.zeros((7, 2), jnp.float32)   # 7 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            jax.shard_map(body, mesh=mesh.mesh, in_specs=Pspec(),
                          out_specs=Pspec("dp"), check_vma=False)(bad)

    def test_partial_ppermute_rejects_indivisible(self):
        import paddle_tpu.parallel as dist
        from paddle_tpu.parallel.mesh import P as Pspec
        mesh = dist.init_mesh(dp=4)
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def body(x):
            return dist.collective.partial_ppermute(x, perm, group="dp")

        bad = jnp.zeros((6, 2), jnp.float32)   # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            jax.shard_map(body, mesh=mesh.mesh, in_specs=Pspec(),
                          out_specs=Pspec(), check_vma=False)(bad)


class TestGradientMergeFp32Feed:
    def test_fp16_k_step_sum_does_not_overflow(self):
        """The fp32 accumulator must reach the optimizer WITHOUT a cast
        back to the grad dtype: an fp16 re-cast of a k-step sum can
        overflow to inf (and bf16 re-cast re-rounds the precision the
        buffer existed to keep)."""
        import paddle_tpu.parallel as dist
        from paddle_tpu.core.tensor import unwrap
        from paddle_tpu.parallel.api import parallel_train_step
        mesh = dist.init_mesh(dp=1)
        net = pt.nn.Linear(4, 4)
        for _n, p in net.named_parameters():
            p._replace_value(unwrap(p).astype(jnp.float16))
        opt = pt.optimizer.Momentum(learning_rate=1e-9, momentum=0.9,
                                    parameters=net.parameters())
        # per-step grad wrt bias = 2 rows * 30000 = 60000 (< fp16 max);
        # the k=2 SUM = 120000 overflows fp16
        step_fn, params, opt_state, _ = parallel_train_step(
            net, lambda out, *a: out.sum() * 30000.0, opt, mesh,
            grad_accum_steps=2, accum_avg=False, donate=False)
        x = np.ones((2, 4), np.float32)
        batch = {"inputs": (x,), "labels": ()}
        for i in (1, 2):
            loss, params, opt_state = step_fn(params, opt_state, batch,
                                              i, None)
        flat = jax.tree_util.tree_leaves(params)
        assert all(bool(jnp.all(jnp.isfinite(p))) for p in flat), \
            "fp16 re-cast of the k-step sum overflowed to inf"
        # params keep their storage dtype; the optimizer inner state is
        # fp32 BY DESIGN for fp16 params (fp16 moments flush tiny v to
        # zero) and must stay dtype-stable through the k-step select
        assert {str(p.dtype) for p in flat} == {"float16"}
        vel = jax.tree_util.tree_leaves(opt_state["_opt"])
        assert {str(x.dtype) for x in vel} == {"float32"}, \
            "fp16-param optimizer state must hold fp32 moments, stably"
        bias = params["bias"] if "bias" in params else flat[0]
        assert float(jnp.asarray(bias).sum()) < 0   # update applied


class TestRoiAlignStaticReplay:
    def test_recorded_program_does_not_bake_record_time_grids(self):
        """Under the static recorder the adaptive grid must NOT be
        derived from record-time box values: the Program replays with
        fresh feeds. The recorder falls back to the fixed 2x2 grid —
        same as the jit-tracing path — so replay(feed) == jit(feed)."""
        import paddle_tpu.static as static
        import paddle_tpu.vision.ops as V
        feat = np.random.RandomState(3).rand(1, 2, 16, 16).astype(
            np.float32)
        # record with TINY boxes (adaptive grid would be 1x1)...
        small = np.array([[1.0, 1.0, 3.0, 3.0]], np.float32)
        # ...replay with BIG boxes (adaptive grid would be 8x8)
        big = np.array([[0.0, 0.0, 15.0, 15.0]], np.float32)
        bn = np.array([1], np.int32)

        prog = static.Program()
        with static.program_guard(prog):
            xv = static.data("x", shape=[1, 2, 16, 16], dtype="float32")
            bv = static.data("boxes", shape=[1, 4], dtype="float32")
            _ = small  # record-time values never enter the graph
            out = V.roi_align(xv, bv, bn, output_size=2)
        exe = static.Executor()
        got = exe.run(prog, feed={"x": feat, "boxes": big},
                      fetch_list=[out])[0]

        want = jax.jit(lambda f, b: V.roi_align(f, b, bn, output_size=2)
                       )(feat, big)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- (g) review r5

class TestLowPrecisionOptimizerDtypes:
    @pytest.mark.parametrize("opt_name", ["Momentum", "RMSProp",
                                          "Adagrad", "Adamax"])
    def test_fp16_params_stay_fp16_one_eager_step(self, opt_name):
        """fp32 moments must not promote fp16 params through
        `p - lr * upd` in ANY optimizer (only Adam/Lamb cast back
        internally)."""
        from paddle_tpu.core.tensor import unwrap
        net = pt.nn.Linear(4, 4)
        for _n, p in net.named_parameters():
            p._replace_value(unwrap(p).astype(jnp.float16))
        opt = getattr(pt.optimizer, opt_name)(
            learning_rate=1e-3, parameters=net.parameters())
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        dts = {str(unwrap(p).dtype) for p in net.parameters()}
        assert dts == {"float16"}, (opt_name, dts)

    def test_adagrad_fp16_accumulator_is_fp32(self):
        """Adagrad's moment must not flush g^2 < 6e-8 to zero."""
        from paddle_tpu.core.tensor import unwrap
        net = pt.nn.Linear(2, 2)
        for _n, p in net.named_parameters():
            p._replace_value(unwrap(p).astype(jnp.float16))
        opt = pt.optimizer.Adagrad(learning_rate=1e-3,
                                   parameters=net.parameters())
        st = opt.init_state({n: unwrap(p)
                             for n, p in net.named_parameters()})
        dts = {str(a.dtype)
               for a in jax.tree_util.tree_leaves(st["moment"])}
        assert dts == {"float32"}, dts


class TestSchedulerOversizedRequest:
    def test_request_bigger_than_max_batch_runs_alone(self):
        from paddle_tpu.inference import BatchScheduler
        sched = BatchScheduler(lambda s: [s[0] * 2.0],
                               max_batch_size=4, max_delay_ms=5)
        big = np.ones((9, 3), np.float32)
        out = sched.submit(big).result(timeout=20)
        sched.close()
        np.testing.assert_allclose(out[0], big * 2.0)


# ------------------------------------------------------- (h) advice r5

def _tiny_lm():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


class TestEosFirstTokenPads:
    """ADVICE r5 #1/#3: a prefill whose argmax IS eos must eos-pad the
    whole output — before the fix, done started all-False and decode
    free-ran real tokens."""

    def test_greedy_generate_stub_model(self):
        from paddle_tpu.inference.decode_loop import greedy_generate
        V = 5

        def embed(tok, t):
            return tok.astype(jnp.float32)[:, None]

        def step(x, caches, t):
            return x, caches

        def head(out):       # next argmax is always prev + 1 (mod V)
            nxt = (out[:, 0].astype(jnp.int32) + 1) % V
            return jax.nn.one_hot(nxt, V)

        ids, _ = greedy_generate(embed, step, head, {},
                                 jnp.array([3], jnp.int32), 0, 5,
                                 eos_token_id=3)
        # before the fix this free-ran to [3, 4, 0, 1, 2]
        np.testing.assert_array_equal(np.asarray(ids)[0], [3, 3, 3, 3, 3])

    def test_generate_real_model_contract(self):
        """generate()'s documented contract: tail padded with eos —
        including when the FIRST generated token is the eos."""
        model = _tiny_lm()
        p = np.random.default_rng(0).integers(0, 256, (4,)).astype(
            np.int32)
        free = model.generate(pt.to_tensor(p[None]), max_new_tokens=4,
                              max_cache_len=32).numpy()[0, 4:]
        eos = int(free[0])          # prefill argmax
        out = model.generate(pt.to_tensor(p[None]), max_new_tokens=4,
                             max_cache_len=32,
                             eos_token_id=eos).numpy()[0, 4:]
        np.testing.assert_array_equal(out, [eos] * 4)

    def test_deploy_decode_eos_first(self, tmp_path):
        from paddle_tpu.inference.deploy_decode import (export_decode,
                                                        load_decode)
        model = _tiny_lm()
        p = np.random.default_rng(1).integers(0, 256, (1, 4)).astype(
            np.int32)
        free = model.generate(pt.to_tensor(p), max_new_tokens=3,
                              max_cache_len=7).numpy()[0, 4:]
        eos = int(free[0])
        prefix = str(tmp_path / "eos_first")
        export_decode(prefix, model, prompt_len=4, max_new_tokens=3,
                      batch=1, eos_token_id=eos)
        got = load_decode(prefix).generate(p)[0, 4:]
        # before the fix the archive free-ran past the eos-first token
        np.testing.assert_array_equal(got, [eos] * 3)

    def test_export_decode_rejects_undersized_cache(self, tmp_path):
        """ADVICE r5 #5: an explicit max_cache_len too small for
        prompt + new tokens must raise, not silently clamp decode
        writes onto the cache's last rows."""
        from paddle_tpu.inference.deploy_decode import export_decode
        model = _tiny_lm()
        with pytest.raises(ValueError, match="max_cache_len"):
            export_decode(str(tmp_path / "x"), model, prompt_len=8,
                          max_new_tokens=8, max_cache_len=12)


class TestPrefixRemainderChunkPad:
    """ADVICE r5 #2: a registered-prefix hit prefills only the
    remainder; when that remainder is LONGER than the chunk, its own
    pad can overflow max_cache_len even though the full-prompt pad fits
    — must be rejected at submit(). Remainders <= chunk run UNCHUNKED
    (generation._run_prefill's direct path, zero pad) and must keep
    being accepted."""

    def test_submit_rejects_prefix_remainder_overflow(self):
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        model = _tiny_lm()
        rng = np.random.default_rng(2)
        prefix = rng.integers(0, 256, (6,)).astype(np.int32)
        prompt = np.concatenate(
            [prefix, rng.integers(0, 256, (6,)).astype(np.int32)])
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=13, prefill_chunk=4)
        srv.register_prefix(prefix)
        # T=12, max_new=1: full-prompt pad is 0 (12 % 4 == 0) so the
        # old check passed (13 <= 13) — but admission prefills the
        # 6-token remainder at t0=6 padded to 8 rows, writing row 14
        with pytest.raises(ValueError, match="pad rows"):
            srv.submit(prompt, max_new_tokens=1)
        # the same-length prompt WITHOUT the prefix hit fits and serves
        other = rng.integers(0, 256, (12,)).astype(np.int32)
        rid = srv.submit(other, max_new_tokens=1)
        assert len(srv.run()[rid]) == 1

    def test_short_remainder_runs_unchunked_and_serves(self):
        """A remainder <= chunk takes the unchunked prefill path (no
        pad): submit must ACCEPT it and tokens must match solo — the
        bound check may not over-estimate (code-review r6)."""
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        model = _tiny_lm()
        rng = np.random.default_rng(6)
        prefix = rng.integers(0, 256, (6,)).astype(np.int32)
        prompt = np.concatenate(
            [prefix, rng.integers(0, 256, (2,)).astype(np.int32)])
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=9, prefill_chunk=4)
        srv.register_prefix(prefix)
        rid = srv.submit(prompt, max_new_tokens=1)   # rem 2 <= chunk 4
        out = srv.run()[rid]
        want = model.generate(pt.to_tensor(prompt[None]),
                              max_new_tokens=1, max_cache_len=9,
                              prefill_chunk=4).numpy()[0, 8:]
        np.testing.assert_array_equal(out, want)

    def test_longest_match_decides_not_worst_case(self):
        """Admission is longest-match-wins and prefixes are never
        removed: a SHORTER matching prefix's larger remainder pad must
        not reject a request the longest match serves (code-review
        r6)."""
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        model = _tiny_lm()
        rng = np.random.default_rng(7)
        p10 = rng.integers(0, 256, (10,)).astype(np.int32)
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=12, prefill_chunk=4)
        srv.register_prefix(p10[:5])   # its remainder (5) would pad 3
        srv.register_prefix(p10[:8])   # longest: remainder 2, unchunked
        rid = srv.submit(p10, max_new_tokens=2)
        out = srv.run()[rid]
        want = model.generate(pt.to_tensor(p10[None]), max_new_tokens=2,
                              max_cache_len=12,
                              prefill_chunk=4).numpy()[0, 10:]
        np.testing.assert_array_equal(out, want)

    def test_register_prefix_refuses_stranding_queued_request(self):
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        model = _tiny_lm()
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 256, (12,)).astype(np.int32)
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=13, prefill_chunk=4)
        srv.submit(prompt, max_new_tokens=1)      # fits: pad 0
        # registering its 6-token head now would pad the queued
        # request's 6-token remainder past the cache — refuse
        with pytest.raises(ValueError, match="register prefixes before"):
            srv.register_prefix(prompt[:6])


class TestAdmissionFailureRecorded:
    """ADVICE r5 #2 (second half): one bad request must be recorded as
    a per-rid failure, not kill the serve thread / drop the queue."""

    def _server_with_poisoned_prefill(self, bad_len):
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        model = _tiny_lm()
        orig = model._run_prefill

        def poisoned(bundle, ids, **kw):
            if ids.shape[1] == bad_len:
                raise RuntimeError("injected prefill failure")
            return orig(bundle, ids, **kw)

        model._run_prefill = poisoned
        return ContinuousBatchingServer(model, max_slots=1,
                                        max_cache_len=32)

    def test_run_serves_the_rest(self):
        srv = self._server_with_poisoned_prefill(bad_len=7)
        rng = np.random.default_rng(4)
        rid_bad = srv.submit(rng.integers(0, 256, (7,)).astype(np.int32),
                             max_new_tokens=4)
        rid_good = srv.submit(rng.integers(0, 256, (5,)).astype(np.int32),
                              max_new_tokens=4)
        outs = srv.run()
        assert rid_bad not in outs and len(outs[rid_good]) == 4
        assert isinstance(srv.failures[rid_bad], RuntimeError)
        # failures are drained PER run — a later clean run must not
        # keep reporting stale records (code-review r6)
        rid2 = srv.submit(rng.integers(0, 256, (5,)).astype(np.int32),
                          max_new_tokens=2)
        assert len(srv.run()[rid2]) == 2
        assert srv.failures == {}

    def test_wait_raises_per_request_not_thread_death(self):
        srv = self._server_with_poisoned_prefill(bad_len=7).start()
        try:
            rng = np.random.default_rng(5)
            rid_bad = srv.submit(
                rng.integers(0, 256, (7,)).astype(np.int32),
                max_new_tokens=4)
            rid_good = srv.submit(
                rng.integers(0, 256, (5,)).astype(np.int32),
                max_new_tokens=4)
            with pytest.raises(RuntimeError,
                               match="failed at admission"):
                srv.wait(rid_bad, timeout=300)
            # the serve thread survived and keeps serving
            assert len(srv.wait(rid_good, timeout=300)) == 4
        finally:
            srv.stop()
