"""Wire transport layer (ISSUE 12): frame layout, typed error
marshalling, snapshot transit, net.* chaos behaviours, and the
frame-corruption fuzz contract — a bad frame fails the ONE affected
call with a typed ``TransportError``, never wedges a waiter, never
kills the receive loop."""
import json
import random
import socket
import struct
import threading

import numpy as np
import pytest

from paddle_tpu.inference import transport
from paddle_tpu.inference.transport import (Connection, MAX_FRAME,
                                            NetDelay, NetDrop, NetSever,
                                            NetTruncate, decode_snapshot,
                                            encode_snapshot, jsonable,
                                            marshal_error,
                                            unmarshal_error)
from paddle_tpu.reliability import (NET_PARTITION, NET_RECV, NET_SEND,
                                    DeadlineExceeded, FaultInjector,
                                    FrameError, QueueFullError,
                                    ReliabilityError, ReplicaLostError,
                                    TransportError, errors, faults)

pytestmark = pytest.mark.net


def _pair(fault_injector=None, registry=None):
    a, b = socket.socketpair()
    return (Connection(a, fault_injector=fault_injector,
                       registry=registry, peer="a"),
            Connection(b, peer="b"))


class TestFraming:
    def test_roundtrip_and_order(self):
        a, b = _pair()
        for i in range(5):
            a.send({"i": i, "payload": "x" * (i * 100)})
        got = [b.recv(timeout=2)["i"] for i in range(5)]
        assert got == list(range(5))
        a.close()
        b.close()

    def test_large_frame_roundtrips(self):
        a, b = _pair()
        msg = {"blob": "y" * 300_000}
        got = {}

        def rx():               # a frame bigger than the kernel buffer
            got["msg"] = b.recv(timeout=10)   # needs a live reader

        th = threading.Thread(target=rx)
        th.start()
        a.send(msg)
        th.join(10)
        assert got.get("msg") == msg

    def test_timeout_is_plain_timeout(self):
        a, b = _pair()
        with pytest.raises(TimeoutError):
            b.recv(timeout=0.05)
        a.send({"late": 1})          # connection still fine afterwards
        assert b.recv(timeout=2) == {"late": 1}

    def test_oversize_outbound_refused_without_desync(self):
        a, b = _pair()
        with pytest.raises(FrameError):
            a.send({"blob": "z" * (MAX_FRAME + 1)})
        a.send({"ok": 1})            # nothing hit the wire: still live
        assert b.recv(timeout=2) == {"ok": 1}

    def test_oversize_inbound_severs(self):
        raw_a, raw_b = socket.socketpair()
        b = Connection(raw_b, peer="b")
        raw_a.sendall(struct.pack("!I", MAX_FRAME + 1) + b"x" * 16)
        with pytest.raises(TransportError):
            b.recv(timeout=2)
        assert b.closed

    def test_peer_close_is_transport_error(self):
        a, b = _pair()
        a.close()
        with pytest.raises(TransportError):
            b.recv(timeout=2)

    def test_garbage_payload_is_frame_error_stream_survives(self):
        """The fuzz contract's foundation: a length-valid frame whose
        payload is not JSON spoils only itself."""
        raw_a, raw_b = socket.socketpair()
        b = Connection(raw_b, peer="b")
        rng = random.Random(7)       # seeded-PRNG chaos pattern
        for _ in range(5):
            junk = bytes(rng.randrange(256) for _ in range(40))
            raw_a.sendall(struct.pack("!I", len(junk)) + junk)
            with pytest.raises(FrameError):
                b.recv(timeout=2)
        ok = json.dumps({"fine": True}).encode()
        raw_a.sendall(struct.pack("!I", len(ok)) + ok)
        assert b.recv(timeout=2) == {"fine": True}

    def test_truncated_frame_then_eof_severs(self):
        raw_a, raw_b = socket.socketpair()
        b = Connection(raw_b, peer="b")
        raw_a.sendall(struct.pack("!I", 100) + b"{\"half\":")
        raw_a.close()
        with pytest.raises(TransportError):
            b.recv(timeout=2)


class TestErrorMarshalling:
    def test_reliability_family_roundtrips_by_type(self):
        for name in ("DeadlineExceeded", "QueueFullError",
                     "ServerClosed", "RequestCancelled",
                     "CircuitOpenError", "ReplicaLostError",
                     "TransportError", "FrameError"):
            cls = getattr(errors, name)
            back = unmarshal_error(marshal_error(cls("boom")))
            assert type(back) is cls
            assert "boom" in str(back)

    def test_structured_ctor_degrades_to_typed_base(self):
        err = errors.CallbackError([("r1", ValueError("bad"))])
        back = unmarshal_error(marshal_error(err))
        assert isinstance(back, ReliabilityError)
        assert "CallbackError" in str(back)

    def test_builtins_roundtrip(self):
        for exc in (TimeoutError("slow"), ValueError("nope"),
                    KeyError("missing")):
            back = unmarshal_error(marshal_error(exc))
            assert type(back) is type(exc)

    def test_unknown_kind_becomes_tagged_runtimeerror(self):
        back = unmarshal_error({"kind": "WeirdVendorError",
                                "message": "huh"})
        assert type(back) is RuntimeError
        assert "WeirdVendorError" in str(back)

    def test_typed_deadline_survives_isinstance_contracts(self):
        back = unmarshal_error(marshal_error(DeadlineExceeded("late")))
        assert isinstance(back, TimeoutError)       # family contract
        assert isinstance(back, ReliabilityError)
        assert not isinstance(back, QueueFullError)


class TestJsonTransit:
    def test_jsonable_numpy_and_sets(self):
        out = jsonable({"a": np.int32(3), "b": np.arange(3),
                        "c": frozenset({2, 1}), "d": (1, "x"),
                        "e": None})
        assert out == {"a": 3, "b": [0, 1, 2], "c": [1, 2],
                       "d": [1, "x"], "e": None}
        json.dumps(out)              # actually serializable

    def test_snapshot_roundtrip_and_fleet_merge(self):
        from paddle_tpu.telemetry import MetricRegistry
        from paddle_tpu.telemetry.exposition import merge_snapshots
        reg = MetricRegistry()
        reg.counter("c_total", "c", labelnames=("k",)) \
           .labels(k="x").inc(3)
        reg.gauge("g", "g").set(7)
        reg.histogram("h_seconds", "h").observe(0.02)
        snap = reg.snapshot()
        back = decode_snapshot(json.loads(json.dumps(
            encode_snapshot(snap))))
        assert back["c_total"]["samples"][("x",)] == 3
        assert back["g"]["samples"][()] == 7
        assert back["h_seconds"]["samples"][()]["count"] == 1
        # a decoded remote snapshot merges with a local one
        merged = merge_snapshots([snap, back])
        assert merged["c_total"]["samples"][("x",)] == 6


class TestNetChaos:
    def test_drop_on_send_loses_frame_connection_lives(self):
        fi = FaultInjector(seed=3).on(NET_SEND, schedule=[0],
                                      error=NetDrop)
        a, b = _pair(fault_injector=fi)
        assert a.send({"n": 0}) is False       # dropped
        assert a.send({"n": 1}) is True
        assert b.recv(timeout=2) == {"n": 1}
        assert fi.fired(NET_SEND) == 1

    def test_delay_on_send_delivers_late(self):
        fi = FaultInjector(seed=3).on(NET_SEND, schedule=[0],
                                      error=NetDelay)
        a, b = _pair(fault_injector=fi)
        assert a.send({"n": 0}) is True
        assert b.recv(timeout=2) == {"n": 0}

    def test_truncate_on_send_severs_both_ends(self):
        fi = FaultInjector(seed=3).on(NET_SEND, schedule=[1],
                                      error=NetTruncate)
        a, b = _pair(fault_injector=fi)
        a.send({"n": 0})
        with pytest.raises(TransportError):
            a.send({"n": 1})
        assert a.closed
        assert b.recv(timeout=2) == {"n": 0}   # frame 0 was fine
        with pytest.raises(TransportError):    # then the broken stream
            while True:
                b.recv(timeout=2)

    def test_sever_on_recv(self):
        fi = FaultInjector(seed=3).on(NET_RECV, schedule=[0],
                                      error=NetSever)
        a, b = _pair()
        b._faults = fi
        a.send({"n": 0})
        with pytest.raises(TransportError):
            b.recv(timeout=2)
        assert b.closed

    def test_drop_on_recv_discards_one_frame(self):
        fi = FaultInjector(seed=3).on(NET_RECV, schedule=[0],
                                      error=NetDrop)
        a, b = _pair()
        b._faults = fi
        a.send({"n": 0})
        a.send({"n": 1})
        assert b.recv(timeout=2) == {"n": 1}   # frame 0 vanished

    def test_partition_checked_on_both_directions(self):
        fi = FaultInjector(seed=3).on(NET_PARTITION, schedule=[0])
        a, b = _pair(fault_injector=fi)
        with pytest.raises(TransportError):
            a.send({"n": 0})
        fi2 = FaultInjector(seed=3).on(NET_PARTITION, schedule=[0])
        c, d = _pair()
        d._faults = fi2
        c.send({"n": 0})
        with pytest.raises(TransportError):
            d.recv(timeout=2)

    def test_connect_fault_refuses_typed(self):
        lst = socket.create_server(("127.0.0.1", 0))
        try:
            addr = lst.getsockname()
            fi = FaultInjector(seed=3).on(faults.NET_CONNECT,
                                          schedule=[0])
            with pytest.raises(TransportError):
                transport.connect(addr, timeout=2, fault_injector=fi)
        finally:
            lst.close()

    @pytest.mark.chaos
    def test_same_seed_same_injection_trace(self):
        """Wire chaos rides the seeded per-point PRNG streams: two
        runs with the same seed and visit sequence fire identically
        (the partition-storm determinism contract)."""
        def run(seed):
            fi = FaultInjector(seed=seed) \
                .on(NET_SEND, probability=0.3, error=NetDrop) \
                .on(NET_RECV, probability=0.2, error=NetDrop)
            a, b = _pair(fault_injector=fi)
            b._faults = fi
            delivered = []
            for i in range(30):
                a.send({"i": i})
            a.close()
            while True:
                try:
                    delivered.append(b.recv(timeout=2)["i"])
                except TransportError:
                    break
            return list(fi.trace), delivered

        t1, d1 = run(11)
        t2, d2 = run(11)
        t3, _ = run(12)
        assert t1 == t2 and d1 == d2
        assert t1 != t3
        assert len(d1) < 30          # the storm actually dropped frames


class TestFuzzOneCallFails:
    """Satellite: truncated / oversized / garbage frames fail exactly
    the affected call, typed — concurrent callers and the receive loop
    survive."""

    def test_receiver_loop_survives_seeded_garbage_storm(self):
        raw_a, raw_b = socket.socketpair()
        b = Connection(raw_b, peer="b")
        rng = random.Random(1234)
        good, bad = 0, 0
        for i in range(40):
            if rng.random() < 0.5:
                junk = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(1, 80)))
                raw_a.sendall(struct.pack("!I", len(junk)) + junk)
            else:
                ok = json.dumps({"i": i}).encode()
                raw_a.sendall(struct.pack("!I", len(ok)) + ok)
        raw_a.close()
        while True:
            try:
                msg = b.recv(timeout=2)
            except FrameError:
                bad += 1             # one frame failed, loop continues
                continue
            except TransportError:
                break                # EOF at the end
            good += 1
        assert good > 0 and bad > 0

    def test_truncate_fails_one_call_not_the_waiter(self):
        """A chaos-truncated SEND raises typed TransportError to that
        caller immediately — the contract that no waiter ever wedges
        on a frame that half-left."""
        fi = FaultInjector(seed=9).on(NET_SEND, schedule=[2],
                                      error=NetTruncate)
        a, _b = _pair(fault_injector=fi)
        a.send({"n": 0})
        a.send({"n": 1})
        t0 = threading.Event()

        def doomed():
            with pytest.raises(TransportError):
                a.send({"n": 2})
            t0.set()

        th = threading.Thread(target=doomed)
        th.start()
        th.join(5)
        assert t0.is_set()


class TestPageFrames:
    """Binary page frames (ISSUE 18): the migration wire format — a
    JSON header riding ahead of raw payload bytes, sha256-verified on
    arrival, with a per-connection ``max_frame_bytes`` so page-heavy
    links raise their own cap without loosening every peer's guard."""

    def test_page_frame_roundtrip_bitexact(self):
        a, b = _pair()
        page = np.arange(2 * 3 * 8 * 4 * 4, dtype=np.float32) \
            .reshape(2, 3, 8, 4, 4)
        assert a.send_pages({"push": "pages", "i": 0, "n": 1,
                             "shape": list(page.shape),
                             "dtype": str(page.dtype)},
                            page.tobytes()) is True
        msg = b.recv(timeout=2)
        assert msg["push"] == "pages"
        back = np.frombuffer(msg["_payload"],
                             dtype=np.dtype(msg["dtype"])) \
            .reshape(msg["shape"])
        np.testing.assert_array_equal(back, page)
        a.close()
        b.close()

    def test_oversized_page_frame_fails_typed_never_hangs(self):
        """The satellite guard: a payload past ``max_frame_bytes``
        raises ``FrameError`` BEFORE any bytes hit the wire — the
        stream stays in sync and the conn loop keeps serving instead
        of wedging a half-sent binary tail."""
        raw_a, raw_b = socket.socketpair()
        a = Connection(raw_a, peer="a", max_frame_bytes=4096)
        b = Connection(raw_b, peer="b", max_frame_bytes=4096)
        with pytest.raises(FrameError):
            a.send_pages({"push": "pages", "i": 0}, b"\x00" * 5000)
        # nothing desynced: control traffic still flows both ways
        assert a.send({"ok": 1}) is True
        assert b.recv(timeout=2) == {"ok": 1}
        small = np.ones(16, dtype=np.float32)
        a.send_pages({"push": "pages", "i": 0, "shape": [16],
                      "dtype": "float32"}, small.tobytes())
        msg = b.recv(timeout=2)
        np.testing.assert_array_equal(
            np.frombuffer(msg["_payload"], dtype=np.float32), small)
        a.close()
        b.close()

    def test_max_frame_bytes_parameterized_per_connection(self):
        """A page-heavy link raises its own cap: the same payload that
        a default conn refuses sails through one constructed with a
        bigger ``max_frame_bytes`` — and the oversize check tracks the
        configured value, not the module constant."""
        big = b"\x01" * (64 * 1024)
        raw_a, raw_b = socket.socketpair()
        small_a = Connection(raw_a, peer="a", max_frame_bytes=1024)
        small_b = Connection(raw_b, peer="b", max_frame_bytes=1024)
        with pytest.raises(FrameError):
            small_a.send_pages({"i": 0}, big)
        small_a.close()
        small_b.close()
        raw_c, raw_d = socket.socketpair()
        wide_c = Connection(raw_c, peer="c",
                            max_frame_bytes=1024 * 1024)
        wide_d = Connection(raw_d, peer="d",
                            max_frame_bytes=1024 * 1024)
        assert wide_c.send_pages({"i": 0}, big) is True
        msg = wide_d.recv(timeout=5)
        assert msg["_payload"] == big
        wide_c.close()
        wide_d.close()

    def test_connect_accepts_max_frame_bytes(self):
        lst = socket.create_server(("127.0.0.1", 0))
        try:
            conn = transport.connect(lst.getsockname(), timeout=2,
                                     max_frame_bytes=123456)
            assert conn.max_frame_bytes == 123456
            conn.close()
        finally:
            lst.close()

    def test_sha256_mismatch_spoils_one_transfer_only(self):
        """A corrupted payload fails its frame typed; framing held, so
        the connection keeps serving — the migration layer above sees
        a checksum miss and degrades to replay."""
        raw_a, raw_b = socket.socketpair()
        b = Connection(raw_b, peer="b")
        blob = b"\x07" * 64
        head = {"push": "pages", "i": 0, "_bin": len(blob),
                "_sha256": "0" * 64}          # wrong digest
        hb = json.dumps(head, separators=(",", ":")).encode()
        raw_a.sendall(struct.pack("!I", len(hb)) + hb + blob)
        with pytest.raises(FrameError):
            b.recv(timeout=2)
        ok = json.dumps({"fine": 1}).encode()
        raw_a.sendall(struct.pack("!I", len(ok)) + ok)
        assert b.recv(timeout=2) == {"fine": 1}
        b.close()

    def test_page_send_chaos_point_targets_only_page_frames(self):
        """``net.page_send`` storms migration traffic without touching
        control frames: a drop armed there swallows the binary frame
        while ordinary sends keep flowing."""
        fi = FaultInjector(seed=4).on(faults.NET_PAGE_SEND,
                                      schedule=[0], error=NetDrop)
        a, b = _pair(fault_injector=fi)
        assert a.send_pages({"i": 0}, b"\x02" * 32) is False  # vanished
        assert a.send({"ctl": 1}) is True
        assert b.recv(timeout=2) == {"ctl": 1}
        assert fi.fired(faults.NET_PAGE_SEND) == 1
        a.close()
        b.close()
