"""Mesh-change checkpoint conversion + elastic kill-relaunch e2e.

Reference capabilities: auto_parallel/converter.py (re-slice checkpoints
across meshes) and the launch controller restart path
(launch/controllers/controller.py:72; elastic manager kill/relaunch —
tested in the reference via test_fleet_launch_elastic.sh with killed
processes)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.parallel as dist
from paddle_tpu.parallel.mesh import P
from paddle_tpu.parallel.checkpoint_converter import (
    build_shardings, convert_state, load_on_mesh, save_for_mesh_change)


class TestMeshChangeRestore:
    def test_dp8_to_dp2xmp4(self, tmp_path):
        rng = np.random.RandomState(0)
        w = rng.randn(16, 8).astype(np.float32)
        b = rng.randn(8).astype(np.float32)

        mesh_a = dist.init_mesh(dp=8)
        sh_a = build_shardings(mesh_a, {"w": w, "b": b},
                               spec_map={"w": P("dp")})
        state = convert_state({"w": jnp.asarray(w), "b": jnp.asarray(b)},
                              sh_a)
        save_for_mesh_change(state, str(tmp_path / "ck"))

        mesh_b = dist.init_mesh(dp=2, mp=4)
        restored = load_on_mesh(str(tmp_path / "ck"), mesh_b,
                                spec_map={"w": P("dp", "mp")})
        np.testing.assert_allclose(np.asarray(restored["w"]), w)
        np.testing.assert_allclose(np.asarray(restored["b"]), b)
        assert restored["w"].sharding.spec == P("dp", "mp")

    def test_name_map_rename(self, tmp_path):
        mesh = dist.init_mesh(dp=2)
        w = jnp.arange(8.0, dtype=jnp.float32)
        save_for_mesh_change({"old_name": w}, str(tmp_path / "ck2"))
        restored = load_on_mesh(str(tmp_path / "ck2"), mesh,
                                name_map={"old_name": "new_name"})
        assert "new_name" in restored
        np.testing.assert_allclose(np.asarray(restored["new_name"]),
                                   np.arange(8.0))

    def test_in_memory_convert(self):
        mesh_a = dist.init_mesh(dp=4)
        x = jax.device_put(jnp.ones((8, 4)),
                           build_shardings(mesh_a, {"x": np.ones((8, 4))},
                                           {"x": P("dp")})["x"])
        mesh_b = dist.init_mesh(dp=2, mp=2)
        y = convert_state(
            {"x": x}, build_shardings(mesh_b, {"x": np.ones((8, 4))},
                                      {"x": P("mp", "dp")}))["x"]
        np.testing.assert_allclose(np.asarray(y), 1.0)
        assert y.sharding.spec == P("mp", "dp")


@pytest.mark.slow
def test_elastic_kill_relaunch(tmp_path):
    """2 real worker processes -> rank 1 crashes -> pod fails -> relaunch
    1 worker on a smaller/reshaped mesh resuming from checkpoint."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "elastic_worker.py")
    ckdir = str(tmp_path / "ckpts")
    os.makedirs(ckdir)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_", "XLA_", "JAX_"))}
    env.update({"CKPT_DIR": ckdir, "TOTAL_STEPS": "6",
                "CRASH_RANK": "1", "CRASH_STEP": "3",
                "PADDLE_MASTER": "127.0.0.1:29731",
                "PYTHONUNBUFFERED": "1"})

    def launch(nproc, phase, extra_env=None):
        e = dict(env)
        e["PHASE"] = phase
        e.update(extra_env or {})
        cmd = [sys.executable, "-m", "paddle_tpu.parallel.launch.main",
               "--nproc_per_node", str(nproc),
               "--log_dir", str(tmp_path / f"log_{phase}"),
               "--max_restart", "0",
               worker]
        return subprocess.run(cmd, env=e, cwd=repo, capture_output=True,
                              text=True, timeout=420)

    # phase 1: rank 1 crashes at step 3; the pod must report failure
    r1 = launch(2, "train")
    assert r1.returncode != 0, (r1.stdout, r1.stderr)
    latest = os.path.join(ckdir, "LATEST")
    assert os.path.exists(latest), "no checkpoint was written before crash"
    saved = int(open(latest).read())
    # rank 1 dies entering its 4th step (index 3); rank 0 may still
    # complete and checkpoint that step before blocking on the barrier
    assert 1 <= saved <= 4

    # phase 2: smaller cluster (1 proc), restore onto dp=2 x mp=2
    r2 = launch(1, "resume")
    assert r2.returncode == 0, (r2.stdout, r2.stderr,
                                open(os.path.join(
                                    str(tmp_path / "log_resume"),
                                    "workerlog.0")).read()[-2000:])
    res = json.load(open(os.path.join(ckdir, "result.json")))
    assert res["resumed_from"] == saved

    # trajectory parity: resumed run must land exactly where an
    # uninterrupted deterministic run lands
    target = np.linspace(-1.0, 1.0, 32).reshape(8, 4).astype(np.float32)
    w = np.zeros((8, 4), np.float32)
    for _ in range(6):
        w = w - 0.1 * (2.0 * (w - target))
    np.testing.assert_allclose(np.asarray(res["final_w"]), w, rtol=1e-5)
    assert res["losses"][-1] < res["losses"][0]
