"""Batched chunked ragged prefill inside the decode tick (ISSUE 6).

Four layers of coverage:

- Kernel: the Pallas ragged-prefill kernel (interpret mode) must match
  the gather reference on packed variable-length segments with prefix
  offsets, skip idle slots, and never read positions beyond a row's
  causal frontier.
- Generation: the paged bundle's ragged-prefill entry point writes
  cache rows and emits last-row logits BIT-IDENTICAL to the dense
  batch-1 prefill — packed multi-slot launches and chunk-straddling
  resumes at t0 > 0 included.
- Server: ``prefill_mode="ragged"`` (the paged default) emits
  bit-identical tokens to the dense backend AND the dense-prefill paged
  baseline (greedy + seeded sampling, mixed lengths of 1 /
  page_size - 1 / page_size / multi-page / chunk-straddling, cold and
  auto-hit), with auto-hits counter-asserted to skip the
  page-gather→dense→scatter detour (``_seed_from_pages`` never runs,
  dispatches-per-admission drop vs the dense baseline).
- Scheduler: the per-tick token budget interleaves long prefills with
  decode (in-flight slots advance EVERY tick — the tick-budget
  starvation invariant), the T-1 cap keeps full-prefix hits serving,
  and mid-prefill slots tear down leak-free on cancel/deadline.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.ops.pallas import ragged_prefill as rp


def _rand(*shape, seed=0, scale=0.5):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)


import functools


@functools.lru_cache(maxsize=1)
def _model():
    # one llama across the module: every parity test uses the same
    # (max_cache_len, page_size) bundles, so sharing the instance
    # shares the compiles through the model's bundle LRU — the suite
    # stays inside the tier-1 wall-clock budget
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _solo(model, ids, n_new, **kw):
    out = model.generate(pt.to_tensor(ids[None]), max_new_tokens=n_new,
                         max_cache_len=64, **kw).numpy()[0]
    return out[len(ids):]


# ------------------------------------------------------------- kernel


class TestRaggedPrefillKernel:
    @pytest.mark.parametrize("kvh,nh", [(2, 2), (2, 4)])  # MHA and GQA
    def test_kernel_matches_gather_oracle(self, kvh, nh):
        S, C, hd, P, pg, maxp = 3, 4, 32, 12, 8, 4
        q = _rand(S, C, nh, hd, seed=1)
        kp = _rand(P, pg, kvh, hd, seed=2)
        vp = _rand(P, pg, kvh, hd, seed=3)
        rng = np.random.RandomState(4)
        bt = jnp.asarray(np.stack([
            rng.choice(np.arange(1, P), maxp, replace=False)
            for _ in range(S)]).astype(np.int32))
        # prefix offsets: cold, mid-page resume, page-boundary resume
        t0 = jnp.asarray(np.array([0, 5, pg], np.int32))
        takes = np.array([C, 2, 3], np.int32)
        out = rp._ragged_prefill_pallas(q, kp, vp, bt, t0,
                                        t0 + jnp.asarray(takes) - 1,
                                        0.2, interpret=True)
        ref = rp._ref_ragged_prefill(q, kp, vp, bt, t0, 0.2)
        for s in range(S):                  # live rows only
            np.testing.assert_allclose(
                np.asarray(out)[s, :takes[s]],
                np.asarray(ref)[s, :takes[s]], rtol=2e-5, atol=2e-5)

    def test_kernel_skips_idle_slots_and_masks_future(self):
        """An idle slot (last = -1) produces no NaN/Inf, and poisoning
        pool rows beyond every row's causal frontier must not change a
        single output bit."""
        S, C, nh, kvh, hd, P, pg, maxp = 2, 4, 2, 2, 16, 8, 4, 4
        q = _rand(S, C, nh, hd, seed=5)
        kp = _rand(P, pg, kvh, hd, seed=6)
        vp = _rand(P, pg, kvh, hd, seed=7)
        bt = jnp.asarray(np.array([[1, 2, 0, 0], [3, 4, 5, 0]],
                                  np.int32))
        t0 = jnp.asarray(np.array([2, 64], np.int32))
        last = jnp.asarray(np.array([2 + 4 - 1, -1], np.int32))
        out1 = rp._ragged_prefill_pallas(q, kp, vp, bt, t0, last, 0.3,
                                         interpret=True)
        assert np.isfinite(np.asarray(out1)).all()
        # slot 0's last visible position is t0+C-1 = 5 (page 1, row 1):
        # poison everything after it
        kp2 = kp.at[2, 2:].set(1e3).at[5:].set(-1e3)
        vp2 = vp.at[2, 2:].set(1e3).at[5:].set(-1e3)
        out2 = rp._ragged_prefill_pallas(q, kp2, vp2, bt, t0, last, 0.3,
                                         interpret=True)
        np.testing.assert_array_equal(np.asarray(out1)[0],
                                      np.asarray(out2)[0])

    def test_wide_chunk_tiles_query_rows(self):
        """Chunks wider than _QUERY_TILE run as several shifted-offset
        launches (bounded VMEM scratch on real TPUs — review finding);
        the tiled composition must match the untiled reference,
        including a slot whose live rows end mid-tile and an idle
        slot."""
        S, C, nh, kvh, hd, P, pg, maxp = 2, 16, 4, 2, 16, 16, 8, 8
        assert C > rp._QUERY_TILE
        q = _rand(S, C, nh, hd, seed=11)
        kp = _rand(P, pg, kvh, hd, seed=12)
        vp = _rand(P, pg, kvh, hd, seed=13)
        bt = jnp.asarray(np.array([[1, 2, 3, 4, 0, 0, 0, 0],
                                   [5, 6, 7, 8, 9, 0, 0, 0]], np.int32))
        t0 = jnp.asarray(np.array([3, 64], np.int32))
        last = jnp.asarray(np.array([3 + 10 - 1, -1], np.int32))
        out = rp.ragged_prefill_attention(q, kp, vp, bt, t0, last=last,
                                          sm_scale=0.25, interpret=True)
        ref = rp._ref_ragged_prefill(q, kp, vp, bt, t0, 0.25)
        np.testing.assert_allclose(np.asarray(out)[0, :10],
                                   np.asarray(ref)[0, :10],
                                   rtol=2e-5, atol=2e-5)
        assert np.isfinite(np.asarray(out)).all()

    def test_ref_path_bitwise_matches_dense_prefill_attend(self):
        """The gather fallback mirrors generation._cached_attend op for
        op at prefill shapes — paging must not change a single bit."""
        from paddle_tpu.models.generation import _cached_attend
        S, C, nh, kvh, hd, T, pg = 2, 5, 4, 2, 16, 32, 8
        maxp = T // pg
        q = _rand(S, C, nh, hd, seed=8)
        kc = _rand(S, T, kvh, hd, seed=9)
        vc = _rand(S, T, kvh, hd, seed=10)
        t0 = jnp.asarray(np.array([3, 11], np.int32))
        kk = jnp.repeat(kc, nh // kvh, axis=2)
        vv = jnp.repeat(vc, nh // kvh, axis=2)
        want = _cached_attend(q, kk, vv, t0, C, 0.25)

        P = 1 + S * maxp
        kp = jnp.zeros((P, pg, kvh, hd), jnp.float32)
        vp = jnp.zeros((P, pg, kvh, hd), jnp.float32)
        bt = np.zeros((S, maxp), np.int32)
        for b in range(S):
            ids = 1 + b * maxp + np.arange(maxp)
            bt[b] = ids
            kp = kp.at[ids].set(kc[b].reshape(maxp, pg, kvh, hd))
            vp = vp.at[ids].set(vc[b].reshape(maxp, pg, kvh, hd))
        got = rp._ref_ragged_prefill(q, kp, vp, jnp.asarray(bt), t0,
                                     0.25)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ------------------------------------------------------- generation layer


class TestRaggedPrefillBundle:
    def test_packed_launch_bitwise_matches_dense_prefill(self):
        """Two slots' prompts in ONE ragged launch: pool rows and
        last-token logits bit-match each prompt's dense batch-1
        prefill; a chunk-straddling two-launch resume at t0 > 0
        bit-matches too."""
        m = _model()
        MCL, PG, NP, S = 64, 8, 33, 2
        dense = m._decode_bundle(MCL)
        paged = m._decode_bundle(MCL, cache_backend="paged",
                                 page_size=PG, num_pages=NP)
        assert len(paged) >= 6          # ragged entry is element 5
        #                                 (element 6 = fused tick, ISSUE 14)
        init_p, ragged_jit = paged[0], paged[5]
        rng = np.random.default_rng(0)
        ids_a = rng.integers(0, 256, (12,)).astype(np.int32)
        ids_b = rng.integers(0, 256, (7,)).astype(np.int32)
        lg_a, cd_a = m._run_prefill(dense, ids_a[None])
        lg_b, cd_b = m._run_prefill(dense, ids_b[None])

        caches = init_p(S)
        bt = np.zeros((S, MCL // PG), np.int32)
        bt[0, :2] = [1, 2]
        bt[1, :1] = [3]
        caches = dict(caches, bt=jnp.asarray(bt))
        C = 16
        toks = np.zeros((S, C), np.int32)
        toks[0, :12] = ids_a
        toks[1, :7] = ids_b
        logits, caches = ragged_jit(
            jnp.asarray(toks), jnp.asarray(np.zeros((S,), np.int32)),
            caches, jnp.asarray(np.array([11, 6], np.int32)))
        np.testing.assert_array_equal(np.asarray(logits[0:1]),
                                      np.asarray(lg_a))
        np.testing.assert_array_equal(np.asarray(logits[1:2]),
                                      np.asarray(lg_b))
        pool_k = np.asarray(caches["pool"]["k"])
        ka = pool_k[:, [1, 2]].reshape(pool_k.shape[0], 16,
                                       *pool_k.shape[3:])[:, :12]
        np.testing.assert_array_equal(ka, np.asarray(cd_a["k"])[:, 0, :12])

        # chunk-straddling: 8 rows, then 4 more resumed at t0=8
        caches2 = init_p(S)
        bt2 = np.zeros((S, MCL // PG), np.int32)
        bt2[0, :2] = [4, 5]
        caches2 = dict(caches2, bt=jnp.asarray(bt2))
        c1 = np.zeros((S, 8), np.int32)
        c1[0, :8] = ids_a[:8]
        _, caches2 = ragged_jit(
            jnp.asarray(c1), jnp.asarray(np.array([0, MCL], np.int32)),
            caches2, jnp.asarray(np.zeros((S,), np.int32)))
        c2 = np.zeros((S, 8), np.int32)
        c2[0, :4] = ids_a[8:12]
        lg2, caches2 = ragged_jit(
            jnp.asarray(c2), jnp.asarray(np.array([8, MCL], np.int32)),
            caches2, jnp.asarray(np.array([3, 0], np.int32)))
        np.testing.assert_array_equal(np.asarray(lg2[0:1]),
                                      np.asarray(lg_a))
        pool_k2 = np.asarray(caches2["pool"]["k"])
        ka2 = pool_k2[:, [4, 5]].reshape(pool_k2.shape[0], 16,
                                         *pool_k2.shape[3:])[:, :12]
        np.testing.assert_array_equal(ka2,
                                      np.asarray(cd_a["k"])[:, 0, :12])


# -------------------------------------------------------- server parity


class TestRaggedServerParity:
    def _three_way(self, model, prompts, n_new, budget=None, **kw):
        """dense backend vs paged+dense prefill vs paged+ragged prefill:
        all three must emit bit-identical per-request tokens. Returns
        the ragged server."""
        seeds = list(range(100, 100 + len(prompts)))
        outs = []
        servers = []
        for mode_kw in ({"cache_backend": "dense"},
                        {"cache_backend": "paged", "page_size": 8,
                         "prefill_mode": "dense"},
                        {"cache_backend": "paged", "page_size": 8,
                         "prefill_mode": "ragged",
                         "prefill_tokens_per_tick": budget}):
            srv = ContinuousBatchingServer(model, max_slots=2,
                                           max_cache_len=64,
                                           **mode_kw, **kw)
            rids = [srv.submit(p, max_new_tokens=n_new, seed=s)
                    for p, s in zip(prompts, seeds)]
            res = srv.run()
            outs.append([res[r] for r in rids])
            servers.append(srv)
        for got_dense_paged, got_ragged, got_dense in zip(
                outs[1], outs[2], outs[0]):
            np.testing.assert_array_equal(got_dense_paged, got_dense)
            np.testing.assert_array_equal(got_ragged, got_dense)
        return servers[2]

    @pytest.mark.slow
    def test_greedy_parity_mixed_lengths(self):
        """Mixed prompt lengths: 1, page_size-1, page_size, multi-page
        — 5 requests through 2 slots (refill mid-run), all three
        prefill paths bit-identical. (slow: 3 servers x 5 requests;
        chunk-straddling + sampled keep three-way parity tier-1.)"""
        model = _model()
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (1, 7, 8, 12, 17)]
        srv = self._three_way(model, prompts, 6)
        assert srv.prefill_mode == "ragged"
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0

    @pytest.mark.slow
    def test_greedy_parity_chunk_straddling_budget(self):
        """A 4-token-per-tick budget slices every prompt across ticks
        at arbitrary (non-page-aligned) cut points; tokens must not
        move a bit."""
        model = _model()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (9, 13, 5)]
        self._three_way(model, prompts, 5, budget=4)

    def test_sampled_parity_seeded(self):
        model = _model()
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 11, 6)]
        self._three_way(model, prompts, 6, do_sample=True,
                        temperature=1.3, top_k=9)

    def test_auto_hit_parity_and_no_seed_detour(self):
        """Acceptance (ISSUE 6): an auto-hit admission in ragged mode
        NEVER calls _seed_from_pages (the page-gather→dense→scatter
        detour) — enforced by poisoning it — and still emits tokens
        bit-identical to a cold run and to solo generate."""
        model = _model()
        rng = np.random.default_rng(4)
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged", page_size=8)

        def _poisoned(pages):
            raise AssertionError("ragged auto-hit took the dense-seed "
                                 "detour")

        srv._seed_from_pages = _poisoned
        donor = rng.integers(0, 256, (12,)).astype(np.int32)
        srv.submit(donor, max_new_tokens=4)
        srv.run()
        p = np.concatenate([donor[:8],
                            rng.integers(0, 256, (3,)).astype(np.int32)])
        rid = srv.submit(p, max_new_tokens=6)
        out = srv.run()[rid]
        np.testing.assert_array_equal(out, _solo(model, p, 6))
        assert srv.stats["prefix_auto_hits"] == 1
        assert srv.stats["prefix_auto_hit_tokens"] == 8

    def test_dispatches_per_admission_drop_vs_dense_baseline(self):
        """Acceptance (ISSUE 6): counter-asserted dispatch reduction on
        the shared-prompt auto-hit workload — the PR-5 dense path pays
        seed-gather + per-request prefill + scatter + 3 state pushes
        per admission; ragged amortizes one launch + 3 batched pushes
        per tick."""
        rng = np.random.default_rng(7)
        system = rng.integers(0, 16, (8,)).astype(np.int32)
        prompts = [np.concatenate(
            [system, rng.integers(0, 16, (3,)).astype(np.int32)])
            for _ in range(6)]

        def run(mode):
            srv = ContinuousBatchingServer(
                StubModel(), max_slots=1, max_cache_len=32,
                cache_backend="paged", page_size=4, prefill_mode=mode)
            for p in prompts:
                rid = srv.submit(p, max_new_tokens=4)
                np.testing.assert_array_equal(srv.run()[rid],
                                              stub_tokens(p, 4))
            assert srv.stats["admissions"] == len(prompts)
            return srv.stats["prefill_dispatches"] / len(prompts)

        dense_rate, ragged_rate = run("dense"), run("ragged")
        assert ragged_rate < dense_rate, \
            f"ragged {ragged_rate} !< dense {dense_rate}"


# ------------------------------------------------------------ scheduler


def _stub_srv(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 32)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 4)
    return ContinuousBatchingServer(StubModel(), **kw)


class TestInterleavedScheduler:
    def test_tick_budget_never_starves_inflight_decode(self):
        """Starvation invariant: while a long prompt streams in under a
        small per-tick budget, an already-decoding slot advances by
        tick_block tokens EVERY tick."""
        srv = _stub_srv(max_slots=2, max_cache_len=32,
                        prefill_tokens_per_tick=3)
        a = np.arange(3, dtype=np.int32)   # fits one 3-token budget
        ra = srv.submit(a, max_new_tokens=20)
        srv.step()                       # a admitted + decoding
        st_a = next(s for s in srv._slots if s is not None)
        assert srv._active.any()
        b = (np.arange(24, dtype=np.int32) * 3) % 16   # long prompt
        rb = srv.submit(b, max_new_tokens=4)
        ticks_while_b_prefills = 0
        while any(s is not None and s.phase == "prefill"
                  for s in srv._slots) or srv._queue:
            before = len(st_a.emitted)
            srv.step()
            ticks_while_b_prefills += 1
            assert len(st_a.emitted) == before + 1, \
                "in-flight decode starved by prefill work"
            assert ticks_while_b_prefills < 50
        # 24 tokens at 3/tick: b's prefill really did span many ticks
        assert ticks_while_b_prefills >= 8
        outs = srv.run()
        np.testing.assert_array_equal(outs[rb], stub_tokens(b, 4))
        np.testing.assert_array_equal(outs[ra], stub_tokens(a, 20))

    def test_multiple_admissions_one_tick(self):
        """Several queued requests are admitted and prefilled in the
        SAME tick (one ragged launch), not serialized one per tick."""
        srv = _stub_srv(max_slots=4)
        prompts = [np.arange(5, dtype=np.int32) + i for i in range(4)]
        rids = [srv.submit(p, max_new_tokens=3) for p in prompts]
        srv.step()
        assert int(srv._active.sum()) == 4          # all admitted
        assert srv.stats["admissions"] == 4
        outs = srv.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(outs[rid], stub_tokens(p, 3))

    def test_admission_cap_limits_reservations_per_pass(self):
        srv = _stub_srv(max_slots=4, max_admissions_per_tick=1)
        for i in range(3):
            srv.submit(np.arange(4, dtype=np.int32) + i,
                       max_new_tokens=2)
        srv.step()
        # two scheduling passes per tick, capped at 1 admission each
        assert srv.stats["admissions"] + len(srv._prefill_fifo) <= 2
        srv.run()

    def test_full_prefix_hit_capped_at_t_minus_1(self):
        """Regression (ISSUE 6 satellite): a prompt FULLY covered by
        cached pages (page-aligned replay) still leaves >= 1 remainder
        token so the ragged launch emits its first-token logits."""
        srv = _stub_srv(max_slots=1)
        p = np.arange(8, dtype=np.int32)         # exactly 2 full pages
        for _ in range(2):
            rid = srv.submit(p, max_new_tokens=4)
            np.testing.assert_array_equal(srv.run()[rid],
                                          stub_tokens(p, 4))
        # replay hit is trimmed to one page: 4 reused + 4 re-prefilled
        assert srv.stats["prefix_auto_hits"] == 1
        assert srv.stats["prefix_auto_hit_tokens"] == 4

    def test_cancel_and_deadline_mid_prefill_leak_free(self):
        from paddle_tpu.telemetry.clock import FakeClock
        fc = FakeClock()
        srv = _stub_srv(max_slots=1, prefill_tokens_per_tick=2,
                        clock=fc)
        usable = srv._kv.num_pages - 1
        long_p = (np.arange(20, dtype=np.int32) * 5) % 16
        ra = srv.submit(long_p, max_new_tokens=4)
        srv.step()                               # mid-prefill
        st = next(s for s in srv._slots if s is not None)
        assert st.phase == "prefill"
        assert srv.cancel(ra) is True
        assert np.asarray(srv._results[ra]).size == 0   # empty partial
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and free + cached == usable

        rb = srv.submit(long_p, max_new_tokens=4, deadline_s=5.0)
        srv.step()
        fc.advance(10.0)                         # expire mid-prefill
        srv.step()
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and free + cached == usable
        assert np.asarray(srv._results[rb]).size == 0
        # the pool still serves afterwards
        rc = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        np.testing.assert_array_equal(
            srv.run()[rc], stub_tokens(np.arange(4, dtype=np.int32), 3))

    def test_donation_of_partial_prefill_is_prefix_only(self):
        """A slot torn down mid-prefill donates ONLY the pages it
        actually wrote — a later identical prompt must not reuse
        unwritten pages (it would emit garbage if it did)."""
        srv = _stub_srv(max_slots=1, prefill_tokens_per_tick=5)
        p = (np.arange(16, dtype=np.int32) * 7) % 16
        ra = srv.submit(p, max_new_tokens=4)
        srv.step()                               # 5 of 16 rows written
        srv.cancel(ra)
        cached_after = srv._prefix.cached_pages
        assert cached_after <= 5 // srv._kv.page_size
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 4))

    def test_ragged_ignores_prefill_chunk_pad_bound(self):
        """Satellite: submit()'s fits-check must not charge the dense
        remainder chunk pad in ragged mode — a prompt that only fits
        unpadded is accepted and served."""
        srv = _stub_srv(max_slots=1, max_cache_len=32, prefill_chunk=8)
        p = (np.arange(29, dtype=np.int32) * 3) % 16   # pad would be 3
        rid = srv.submit(p, max_new_tokens=3)          # 29 + 3 == 32
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 3))
        with pytest.raises(ValueError, match="max_cache_len"):
            srv.submit(p, max_new_tokens=4)            # 29 + 4 > 32

    def test_submit_counts_pinned_sharing_in_fit_check(self):
        """Review regression: a request that only fits the pool by
        sharing a PINNED (register_prefix) page run must be accepted in
        ragged mode — the submit-time fit check counts the stable
        pinned run, not the raw full extent."""
        srv = _stub_srv(max_slots=1, max_cache_len=32, num_pages=9)
        prefix = (np.arange(16, dtype=np.int32) * 3) % 16
        srv.register_prefix(prefix)          # pins 4 of 8 usable pages
        p = np.concatenate([prefix,
                            np.asarray([1, 2, 3, 4], np.int32)])
        # extent 20 + 8 = 28 tokens = 7 pages; only 4 are unpinned, but
        # the pinned 4-page run is shared by reference
        rid = srv.submit(p, max_new_tokens=8)
        np.testing.assert_array_equal(srv.run()[rid], stub_tokens(p, 8))
        # a request that can NEVER fit still fails fast
        q = (np.arange(24, dtype=np.int32) * 5) % 16   # no shared run
        with pytest.raises(ValueError, match="grow num_pages"):
            srv.submit(q, max_new_tokens=8)

    def test_admission_cap_applies_in_dense_mode_too(self):
        """Review regression: max_admissions_per_tick must not be an
        inert switch under prefill_mode='dense'."""
        srv = _stub_srv(max_slots=4, prefill_mode="dense",
                        max_admissions_per_tick=1)
        for i in range(4):
            srv.submit(np.arange(4, dtype=np.int32) + i,
                       max_new_tokens=2)
        srv.step()
        assert srv.stats["admissions"] <= 2    # two capped passes
        srv.run()

    def test_config_guards(self):
        with pytest.raises(ValueError, match="max_admissions_per_tick"):
            _stub_srv(max_admissions_per_tick=0)
        with pytest.raises(ValueError, match="prefill_mode"):
            _stub_srv(prefill_mode="bogus")
        with pytest.raises(ValueError, match="ragged"):
            ContinuousBatchingServer(StubModel(), max_cache_len=32,
                                     prefill_mode="ragged")
        with pytest.raises(ValueError, match="prefill_tokens_per_tick"):
            _stub_srv(prefill_tokens_per_tick=0)
        # a paged bundle without the ragged entry falls back to dense
        class OldStub(StubModel):
            def _decode_bundle(self, *a, **kw):
                return StubModel._decode_bundle(self, *a, **kw)[:5]

        srv = ContinuousBatchingServer(OldStub(), max_cache_len=32,
                                       cache_backend="paged",
                                       page_size=4)
        assert srv.prefill_mode == "dense"
