"""Kill-drill acceptance (ISSUE 12): real process boundaries.

Every prior failover test "killed" a replica with a method call. Here
the replica is a SPAWNED PROCESS behind the wire protocol and the
crash is ``SIGKILL`` — no atexit, no drain, no goodbye frame — under a
20-30% ``net.*`` fault storm. The drill asserts the full robustness
chain end to end:

- the supervisor detects the loss (heartbeats stop with the wire),
- the evacuated queue REPLAYS BIT-EXACT on the sibling process
  (greedy and seeded-sampled chains; seeds were resolved at router
  submit),
- requests caught mid-decode flush their streamed partials,
- survivors leak zero pool pages,
- and the failed-over request's journey renders as ONE connected flow
  across process boundaries in the fleet Perfetto trace.

Spawned processes pay a fresh interpreter + first decode compile each
(~5 s on this 1-cpu CPU box), so this file keeps the fleet small; it
is the slowest of the ``net`` suites but inside the tier-1 budget.
"""
import json
import os
import signal
import socket
import time

import numpy as np
import pytest

from _remote_stub import make_stub_server
from _serving_stub import StubModel
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.remote import RemoteReplica, spawn_replica_host
from paddle_tpu.inference.router import ReplicaRouter
from paddle_tpu.inference.transport import NetDrop
from paddle_tpu.reliability import (NET_CONNECT, NET_RECV, NET_SEND,
                                    FaultInjector, QueueFullError,
                                    ReplicaLostError)


def _loopback_available():
    try:
        s = socket.create_server(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


pytestmark = [
    pytest.mark.net,
    pytest.mark.skipif(not _loopback_available(),
                       reason="cannot bind a loopback socket here"),
]

SERVER_KW = {"max_slots": 2, "max_cache_len": 64, "page_size": 8}


@pytest.fixture
def procs():
    spawned = []
    yield spawned
    for proc in spawned:
        if proc.is_alive():
            proc.kill()
        proc.join(10)


@pytest.mark.parametrize(
    "do_sample",
    [False,
     # the greedy drill stays tier-1; sampled doubles the spawn+compile
     # cost to cover seed replay, which test_fused_tick/test_preemption
     # already pin in-process
     pytest.param(True, marks=pytest.mark.slow)],
    ids=["greedy", "sampled"])
def test_sigkill_drill_under_net_storm(procs, tmp_path, do_sample):
    server_kw = dict(SERVER_KW, do_sample=do_sample, telemetry=True)
    if do_sample:
        server_kw["temperature"] = 1.3
    addrs = []
    for _ in range(2):
        proc, addr = spawn_replica_host(
            make_stub_server, server_kw, heartbeat_s=0.05,
            start_server=True)
        procs.append(proc)
        addrs.append(addr)
    fi = FaultInjector(seed=42, enabled=False) \
        .on(NET_SEND, probability=0.25, error=NetDrop) \
        .on(NET_RECV, probability=0.20, error=NetDrop) \
        .on(NET_CONNECT, probability=0.25)
    reps = [RemoteReplica(addr, fault_injector=fi, call_timeout_s=1.0,
                          dead_after_s=0.6, draining_after_s=0.3)
            for addr in addrs]
    router = ReplicaRouter(reps, policy="least_loaded", journeys=True,
                           recorder=True)
    router.start(poll_interval=0.05, start_replicas=False)
    def submit_retrying(p, n, deadline):
        # a real client retries transient fleet-wide refusals: the
        # storm drops dispatch frames, and on this 1-cpu box a child's
        # first decode COMPILE can starve its heartbeat thread long
        # enough to look momentarily dead
        while True:
            try:
                return router.submit(p, max_new_tokens=n)
            except (ReplicaLostError, QueueFullError):
                assert time.monotonic() < deadline, \
                    "fleet never accepted a submit"
                time.sleep(0.05)

    try:
        # warm both children's decode compiles OUTSIDE the storm so
        # the kill lands mid-decode, not mid-compile
        deadline = time.monotonic() + 120
        warm = [submit_retrying(np.asarray([9, i + 1], np.int32), 2,
                                deadline) for i in range(4)]
        for rid in warm:
            router.wait(rid, timeout=120)

        K, budget = 8, 20
        prompts = [np.asarray([5, 3, i + 1], np.int32) for i in range(K)]
        fi.arm()                         # the 20-30% net.* storm is ON
        deadline = time.monotonic() + 90
        rids = [submit_retrying(p, budget, deadline) for p in prompts]
        seeds = {}
        with router._lock:
            for rid in rids:
                seeds[rid] = router._routes[rid].item.seed

        # SIGKILL a replica that is BOTH mid-decode (>= 1 request
        # already streaming -> a partial to flush) and holding queued
        # work (>= 1 request with no tokens -> a bit-exact requeue):
        # the drill then must exercise both failover paths
        deadline = time.monotonic() + 60
        victim = None
        while victim is None:
            for idx, rep in enumerate(reps):
                queued, decoding = rep._mirror_counts()
                if queued >= 1 and decoding >= 1:
                    victim = idx
                    break
            if victim is None:
                assert time.monotonic() < deadline, \
                    "fleet never reached mid-decode-with-backlog " \
                    "under the storm"
                time.sleep(0.005)
        with router._lock:               # ROUTER rids routed to the
            victim_rids = {rid for rid, rt in     # victim at kill time
                           router._routes.items() if rt.idx == victim}
        os.kill(procs[victim].pid, signal.SIGKILL)
        procs[victim].join(10)

        # supervisor detects (wire death = heartbeats stop), evacuates,
        # requeues onto the surviving PROCESS; then calm the storm so
        # the drain converges promptly
        deadline = time.monotonic() + 60
        while router.stats["evacuations"] < 1 \
                or router.stats["requeued"] < 1:
            assert time.monotonic() < deadline, \
                f"no failover observed: {router.stats}"
            time.sleep(0.02)
        fi.disarm()

        results = {rid: router.wait(rid, timeout=120) for rid in rids}

        # bit-exact parity against a local oracle server running the
        # SAME resolved seeds: full results must match exactly, a
        # flushed partial must be an exact prefix
        oracle_kw = {k: v for k, v in server_kw.items()
                     if k != "telemetry"}
        oracle = ContinuousBatchingServer(StubModel(), **oracle_kw)
        orid = {rid: oracle.submit(p, max_new_tokens=budget,
                                   seed=seeds[rid])
                for rid, p in zip(rids, prompts)}
        expected = oracle.run()
        full = partial = 0
        for rid in rids:
            exp, got = expected[orid[rid]], results[rid]
            if len(got) == len(exp):
                np.testing.assert_array_equal(got, exp)
                full += 1
            else:
                assert len(got) < len(exp)
                np.testing.assert_array_equal(got, exp[:len(got)])
                partial += 1
                assert rid in victim_rids   # only the crash flushes
        assert full + partial == K
        assert full >= 1                    # something replayed whole
        assert partial >= 1                 # the mid-decode flush ran

        # zero page leaks on the survivor, over the wire
        survivor = reps[1 - victim]
        bal = survivor.pool_balance()
        assert bal is not None and bal[1] == 0, f"leaked: {bal}"

        # the failed-over journey is ONE connected flow across
        # process boundaries in the merged fleet trace. Prefer a
        # requeued rid whose survivor-side journey pushes survived the
        # storm (they are push frames — the drop chaos can eat them),
        # else any fully replayed victim rid: router + dead-replica
        # pids already prove the boundary crossing.
        replayed = [rid for rid in rids if rid in victim_rids
                    and len(results[rid]) == budget]
        assert replayed
        survivor_where = f"replica{1 - victim}"
        requeued_rid = next(
            (rid for rid in replayed
             if any(e["where"] == survivor_where
                    for e in router._jrec.journey(f"r{rid}") or ())),
            replayed[0])
        path = tmp_path / "fleet.json"
        router.export_fleet_trace(str(path))
        evs = json.loads(path.read_text())["traceEvents"]
        flows = [e for e in evs if e.get("cat") == "journey"
                 and e.get("id") == f"r{requeued_rid}"]
        assert len(flows) >= 2
        assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
        pids = {e["pid"] for e in flows}
        assert len(pids) >= 2               # crossed a process boundary
    finally:
        router.stop(drain=False, timeout=20, stop_replicas=False)
        for rep in reps:
            rep.close()
