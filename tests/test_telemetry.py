"""Telemetry subsystem (paddle_tpu/telemetry): metric registry, trace
spans, Prometheus exposition, and the serving SLO instrumentation —
everything on a FAKE clock so TTFT/TPOT/queue-wait assertions are exact
(no sleeps, no wall-time flake)."""
import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.telemetry import (FakeClock, MetricRegistry, MetricsServer,
                                  NULL_INSTRUMENT, NULL_SPAN,
                                  ServerTelemetry, Tracer,
                                  parse_prometheus, render_prometheus)


def _model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _scripted_telemetry():
    fc = FakeClock()
    reg = MetricRegistry()
    return ServerTelemetry(registry=reg, clock=fc,
                           tracer=Tracer(clock=fc)), fc, reg


def _hist(reg, name, labels=None):
    m = reg.get(name)
    child = m.labels(**labels) if labels else m
    return child.count, child.sum


# ------------------------------------------------------------- registry

class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricRegistry()
        c = reg.counter("c_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1)
        g = reg.gauge("g")
        g.set(7)
        g.inc(3)
        g.dec(1)
        assert g.value == 9.0
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.1, 0.5, 5.0):     # le is INCLUSIVE: 0.1 -> le=0.1
            h.observe(v)
        snap = h.samples()[()]
        assert snap["buckets"] == [(0.1, 2), (1.0, 3), ("+Inf", 4)]
        assert snap["count"] == 4 and snap["sum"] == pytest.approx(5.65)

    def test_labels(self):
        reg = MetricRegistry()
        c = reg.counter("req_total", labelnames=("state",))
        c.labels(state="ok").inc(2)
        c.labels(state="err").inc()
        assert c.labels(state="ok").value == 2.0
        with pytest.raises(ValueError, match="expected labels"):
            c.labels(wrong="x")
        with pytest.raises(ValueError, match="bind them"):
            c.inc()          # labeled metric needs .labels() first

    def test_idempotent_and_conflicting_registration(self):
        reg = MetricRegistry()
        a = reg.counter("x_total", labelnames=("k",))
        assert reg.counter("x_total", labelnames=("k",)) is a
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("x_total", labelnames=("other",))

    def test_thread_safety_exact_totals(self):
        import threading
        reg = MetricRegistry()
        c = reg.counter("n_total")
        h = reg.histogram("v", buckets=(10.0,))

        def work():
            for _ in range(1000):
                c.inc()
                h.observe(1.0)

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == 8000.0
        assert h.count == 8000 and h.sum == pytest.approx(8000.0)


class TestDisabledRegistry:
    def test_null_instruments_shared_and_free(self):
        reg = MetricRegistry(enabled=False)
        c = reg.counter("a_total")
        assert c is NULL_INSTRUMENT
        assert c.labels(anything="x") is NULL_INSTRUMENT
        c.inc()
        reg.gauge("g").set(5)
        reg.histogram("h").observe(1.0)
        assert reg.snapshot() == {}
        assert render_prometheus(reg) == "\n"

    def test_disabled_tracer_reads_no_clock(self):
        fc = FakeClock()
        tr = Tracer(clock=fc, enabled=False)
        with tr.span("x", k=1):
            pass
        tr.instant("y")
        assert tr.span("z") is NULL_SPAN
        assert fc.reads == 0 and tr.events() == []

    def test_disabled_server_telemetry_reads_no_clock(self):
        """The SLO layer's contract: with a disabled registry every
        lifecycle hook is a no-op — zero clock reads, zero samples."""
        fc = FakeClock()
        tele = ServerTelemetry(registry=MetricRegistry(enabled=False),
                               clock=fc)
        tele.on_submit(0, 8, 1)
        tele.on_admit(0, 0)
        tele.on_first_token(0, 8, 0)
        assert tele.tick_started() is None
        tele.on_tick(None, 1, 1)
        tele.on_finish(0, 4)
        tele.set_pool(1, 2, 3)
        tele.add_null_writes(5)
        assert fc.reads == 0
        assert tele.registry.snapshot() == {}
        assert tele.tracer.events() == []

    def test_server_with_disabled_telemetry_skips_hot_path(self):
        tele = ServerTelemetry(registry=MetricRegistry(enabled=False),
                               clock=FakeClock())
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        srv = ContinuousBatchingServer(_model(), max_slots=1,
                                       max_cache_len=32, telemetry=tele)
        assert srv._tele is None            # single attr check per call
        rid = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        assert len(srv.run()[rid]) == 3
        assert tele.clock.reads == 0


# -------------------------------------------------------------- tracing

class TestTracing:
    def test_span_timing_and_args(self):
        fc = FakeClock()
        tr = Tracer(clock=fc)
        with tr.span("prefill", tokens=128) as sp:
            fc.advance(0.5)
            sp.set(chunks=2)
        (ev,) = tr.events()
        assert ev["name"] == "prefill" and ev["ph"] == "X"
        assert ev["ts"] == 0.0 and ev["dur"] == pytest.approx(5e5)
        assert ev["args"] == {"tokens": 128, "chunks": 2}

    def test_cross_scope_span_and_decorator(self, tmp_path):
        fc = FakeClock()
        tr = Tracer(clock=fc)
        sp = tr.begin_span("queued", rid=1)      # ends on another path
        fc.advance(2.0)

        @tr.trace("work")
        def work():
            fc.advance(1.0)
            return 42

        assert work() == 42
        sp.end()
        sp.end()                                  # double end: no-op
        names = {e["name"]: e for e in tr.events()}
        assert names["work"]["dur"] == pytest.approx(1e6)
        assert names["queued"]["dur"] == pytest.approx(3e6)
        out = tmp_path / "trace.json"
        assert tr.export_chrome_trace(str(out)) == 2
        data = json.loads(out.read_text())
        assert {e["name"] for e in data["traceEvents"]} == {"queued",
                                                            "work"}

    def test_max_events_bounds_memory(self):
        tr = Tracer(clock=FakeClock(), max_events=2)
        for _ in range(4):
            with tr.span("s"):
                pass
        assert len(tr.events()) == 2 and tr.dropped == 2

    def test_record_event_interop(self):
        """annotate=True mirrors spans into profiler.RecordEvent (jax
        TraceAnnotation) without breaking span collection."""
        tr = Tracer(clock=FakeClock(), annotate=True)
        with tr.span("annotated"):
            pass
        assert tr.events()[0]["name"] == "annotated"


# ------------------------------------------------------------ exposition

class TestPrometheusExposition:
    def test_round_trip_through_parser(self):
        reg = MetricRegistry()
        c = reg.counter("req_total", "requests", labelnames=("state",))
        c.labels(state="ok").inc(3)
        c.labels(state='we"ird\\l').inc()       # label escaping
        reg.gauge("depth", "queue depth").set(2.5)
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.7)
        text = render_prometheus(reg)
        parsed = parse_prometheus(text)
        assert parsed[("req_total", (("state", "ok"),))] == 3.0
        assert parsed[("req_total", (("state", 'we"ird\\l'),))] == 1.0
        assert parsed[("depth", ())] == 2.5
        assert parsed[("lat_seconds_bucket", (("le", "0.1"),))] == 1.0
        assert parsed[("lat_seconds_bucket", (("le", "+Inf"),))] == 2.0
        assert parsed[("lat_seconds_sum", ())] == pytest.approx(0.75)
        assert parsed[("lat_seconds_count", ())] == 2.0
        # every rendered sample line survives the round trip
        n_samples = sum(1 for line in text.splitlines()
                        if line and not line.startswith("#"))
        assert len(parsed) == n_samples

    def test_http_metrics_and_stats(self):
        import urllib.request
        reg = MetricRegistry()
        reg.counter("hits_total").inc(7)
        with MetricsServer(reg, port=0,
                           extra_stats=lambda: {"extra": 1}) as ms:
            txt = urllib.request.urlopen(
                ms.url + "/metrics", timeout=10).read().decode()
            stats = json.loads(urllib.request.urlopen(
                ms.url + "/stats", timeout=10).read())
            with pytest.raises(Exception):
                urllib.request.urlopen(ms.url + "/nope", timeout=10)
        assert parse_prometheus(txt)[("hits_total", ())] == 7.0
        assert stats["stats"] == {"extra": 1}
        assert stats["metrics"]["hits_total"]["samples"][0]["value"] == 7.0


# ----------------------------------------------------- serving SLO stack

class TestServerSLO:
    def test_scripted_run_exact_histograms(self):
        """Dense server, fake clock: submit a@t=0 and b@t=1, admit both
        at t=2, tick every 0.5s -> every latency histogram is exact."""
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        tele, fc, reg = _scripted_telemetry()
        srv = ContinuousBatchingServer(_model(), max_slots=2,
                                       max_cache_len=64, telemetry=tele)
        rng = np.random.default_rng(0)
        ra = srv.submit(rng.integers(0, 256, (4,)).astype(np.int32),
                        max_new_tokens=4)
        fc.advance(1.0)
        rb = srv.submit(rng.integers(0, 256, (5,)).astype(np.int32),
                        max_new_tokens=3)
        fc.advance(1.0)
        while srv.step():
            fc.advance(0.5)
        outs = srv.run()
        assert set(outs) == {ra, rb}

        req = reg.get("serving_requests_total")
        assert req.labels(state="submitted").value == 2.0
        assert req.labels(state="finished").value == 2.0
        assert req.labels(state="failed").value == 0.0
        # a waits 2s, b waits 1s; first token lands at admission
        assert _hist(reg, "serving_queue_wait_seconds") == (2, 3.0)
        assert _hist(reg, "serving_ttft_seconds") == (2, 3.0)
        # b finishes at t=2.5 (3 tokens), a at t=3.0 (4 tokens)
        assert _hist(reg, "serving_e2e_seconds") == \
            (2, pytest.approx(1.5 + 3.0))
        assert _hist(reg, "serving_tpot_seconds") == \
            (2, pytest.approx(0.5 / 2 + 1.0 / 3))
        # 3 ticks: occupancy 2, 2, 1; decode tokens 2 + 2 + 1
        assert _hist(reg, "serving_tick_occupancy") == (3, 5.0)
        n_ticks, tick_sum = _hist(reg, "serving_tick_seconds")
        assert n_ticks == 3 and tick_sum == 0.0     # fake clock: 0-dur
        tok = reg.get("serving_tokens_total")
        assert tok.labels(kind="prefill").value == 9.0
        assert tok.labels(kind="decode").value == 5.0
        assert tok.labels(kind="prefix_hit").value == 0.0
        pfx = reg.get("serving_prefix_cache_total")
        assert pfx.labels(result="hit").value == 0.0
        assert pfx.labels(result="miss").value == 2.0
        assert reg.get("serving_queue_depth").value == 0.0
        assert reg.get("serving_active_slots").value == 0.0

    def test_request_lifecycle_spans(self):
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        tele, fc, reg = _scripted_telemetry()
        srv = ContinuousBatchingServer(_model(), max_slots=1,
                                       max_cache_len=32, telemetry=tele)
        rid = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        fc.advance(2.0)
        while srv.step():
            fc.advance(0.5)
        srv.run()
        evs = tele.tracer.events()
        spans = {e["name"]: e for e in evs}
        assert spans["request.queued"]["args"]["rid"] == rid
        assert spans["request.queued"]["dur"] == pytest.approx(2e6)
        # prefill span sits between queued and decode (0-dur: the fake
        # clock does not advance inside one step() call)
        assert spans["request.prefill"]["ts"] == pytest.approx(2e6)
        assert spans["request.prefill"]["args"]["prefill_tokens"] == 4
        # first token at t=2; tick at t=2 emits token 2, the t=2.5 tick
        # emits token 3 and the same step harvests -> decode span 0.5s
        assert spans["request.decode"]["dur"] == pytest.approx(5e5)
        assert spans["request.decode"]["args"]["tokens"] == 3

    def test_cancel_and_queue_depth(self):
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        tele, fc, reg = _scripted_telemetry()
        srv = ContinuousBatchingServer(_model(), max_slots=1,
                                       max_cache_len=32, telemetry=tele)
        ra = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=8)
        rb = srv.submit(np.arange(5, dtype=np.int32), max_new_tokens=8)
        assert reg.get("serving_queue_depth").value == 2.0
        assert srv.cancel(rb)
        assert reg.get("serving_queue_depth").value == 1.0
        srv.step()
        assert srv.cancel(ra)                      # mid-decode
        req = reg.get("serving_requests_total")
        assert req.labels(state="canceled").value == 2.0
        assert req.labels(state="finished").value == 0.0

    def test_active_slots_gauge_clears_on_pre_decode_harvest(self):
        """code-review r6: a slot admitted by the previous tick's tail
        that finishes without decoding (budget 1) is harvested BEFORE
        the decode dispatch — the early return must still zero the
        active-slots gauge, not leave a phantom busy slot."""
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        tele, fc, reg = _scripted_telemetry()
        srv = ContinuousBatchingServer(_model(), max_slots=1,
                                       max_cache_len=32, telemetry=tele)
        ra = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=4)
        rb = srv.submit(np.arange(5, dtype=np.int32), max_new_tokens=1)
        while srv.step():
            fc.advance(0.5)
        srv.step()                       # idle tick must also report 0
        assert reg.get("serving_active_slots").value == 0.0
        outs = srv.run()
        assert len(outs[ra]) == 4 and len(outs[rb]) == 1

    def test_paged_pool_gauges_prefix_hits_null_writes(self):
        """Paged backend: page-pool occupancy gauges and the
        null-redirected-write counter match hand-computed values."""
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        tele, fc, reg = _scripted_telemetry()
        srv = ContinuousBatchingServer(_model(), max_slots=2,
                                       max_cache_len=64,
                                       cache_backend="paged", page_size=8,
                                       telemetry=tele)
        usable = srv._kv.num_pages - 1              # 2*8 = 16
        rng = np.random.default_rng(4)
        prefix = rng.integers(0, 256, (8,)).astype(np.int32)
        srv.register_prefix(prefix)                 # pins 1 full page
        pool = reg.get("kv_pool_pages")
        assert pool.labels(state="pinned").value == 1.0
        assert pool.labels(state="free").value == usable - 1
        assert pool.labels(state="live").value == 0.0

        prompt = np.concatenate(
            [prefix, rng.integers(0, 256, (4,)).astype(np.int32)])
        rid = srv.submit(prompt, max_new_tokens=4)  # extent 16 -> 2 pages
        srv.step()                                  # admit: 1 own page
        assert pool.labels(state="live").value == 1.0
        assert pool.labels(state="free").value == usable - 2
        pfx = reg.get("serving_prefix_cache_total")
        assert pfx.labels(result="hit").value == 1.0
        tok = reg.get("serving_tokens_total")
        assert tok.labels(kind="prefix_hit").value == 8.0
        assert tok.labels(kind="prefill").value == 8.0 + 4.0  # reg + rest

        out = srv.run()[rid]
        assert len(out) == 4
        # finished: own page freed, shared page back to pinned-only
        assert pool.labels(state="live").value == 0.0
        assert pool.labels(state="free").value == usable - 1
        assert pool.labels(state="pinned").value == 1.0
        # each tick stepped 1 inactive slot whose writes null-redirect
        n_ticks, _ = _hist(reg, "serving_tick_occupancy")
        assert reg.get("kv_null_redirected_writes_total").value == n_ticks
        # allocator churn counters (kv_cache telemetry_stats)
        ks = srv._kv.telemetry_stats()
        assert ks["alloc_total"] == 2 and ks["freed_total"] == 1
        assert ks["shared_ref_total"] == 1

    def test_admission_failure_counted(self):
        tele, fc, reg = _scripted_telemetry()
        tele.on_submit(7, 8, 1)
        tele.on_admit(7, 0)
        tele.on_admission_failure(7, ValueError("boom"))
        req = reg.get("serving_requests_total")
        assert req.labels(state="failed").value == 1.0
        (ev,) = [e for e in tele.tracer.events()
                 if e["name"] == "request.failed"]
        assert ev["args"] == {"rid": 7, "error": "ValueError"}

    def test_serve_metrics_http_hook(self):
        import urllib.request
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        from paddle_tpu.inference.serving import serve_metrics
        srv = ContinuousBatchingServer(_model(), max_slots=1,
                                       max_cache_len=32,
                                       cache_backend="paged", page_size=8,
                                       telemetry=True)
        rid = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        srv.run()
        ms = serve_metrics(srv)
        try:
            txt = urllib.request.urlopen(
                ms.url + "/metrics", timeout=10).read().decode()
            stats = json.loads(urllib.request.urlopen(
                ms.url + "/stats", timeout=10).read())
        finally:
            ms.close()
        parsed = parse_prometheus(txt)
        assert parsed[("serving_requests_total",
                       (("state", "finished"),))] == 1.0
        assert stats["stats"]["prefill_tokens"] == 4
        assert stats["stats"]["kv_pool"]["num_pages"] == srv._kv.num_pages

    def test_serve_metrics_requires_telemetry(self):
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        from paddle_tpu.inference.serving import serve_metrics
        srv = ContinuousBatchingServer(_model(), max_slots=1,
                                       max_cache_len=32)
        with pytest.raises(ValueError, match="telemetry"):
            serve_metrics(srv)


# --------------------------------------------------- scheduler + training

class TestSchedulerMetrics:
    def test_batch_scheduler_publishes(self):
        from paddle_tpu.inference.serving import BatchScheduler
        reg = MetricRegistry()
        sched = BatchScheduler(lambda xs: [xs[0] * 2.0], max_batch_size=8,
                               max_delay_ms=5, registry=reg)
        futs = [sched.submit(np.ones((2, 3), np.float32))
                for _ in range(3)]
        for f in futs:
            f.result(timeout=20)
        sched.close()
        assert reg.get("scheduler_requests_total").value == 3.0
        assert reg.get("scheduler_batches_total").value >= 1.0
        h = reg.get("scheduler_batch_rows")
        assert h.sum == 6.0                     # 3 requests x 2 rows
        assert reg.get("scheduler_queue_wait_seconds").count == 3

    def test_failure_counter(self):
        from paddle_tpu.inference.serving import BatchScheduler
        reg = MetricRegistry()
        sched = BatchScheduler(lambda xs: 1 / 0, max_delay_ms=1,
                               registry=reg)
        f = sched.submit(np.ones((1, 2), np.float32))
        with pytest.raises(ZeroDivisionError):
            f.result(timeout=20)
        sched.close()
        assert reg.get("scheduler_failures_total").value == 1.0

    def test_rejected_submit_not_counted(self):
        """code-review r6: a submit() on a closed scheduler raises and
        must NOT bump scheduler_requests_total."""
        from paddle_tpu.inference.serving import BatchScheduler
        reg = MetricRegistry()
        sched = BatchScheduler(lambda xs: [xs[0]], registry=reg)
        sched.submit(np.ones((1, 2), np.float32)).result(timeout=20)
        sched.close()
        with pytest.raises(RuntimeError, match="closed"):
            sched.submit(np.ones((1, 2), np.float32))
        assert reg.get("scheduler_requests_total").value == 1.0


class TestTrainingBridge:
    def test_hapi_callback_metrics(self):
        from paddle_tpu.hapi.callbacks import TelemetryCallback
        fc = FakeClock()
        reg = MetricRegistry()
        cb = TelemetryCallback(reg, clock=fc, tokens_per_batch=256,
                               tracer=Tracer(clock=fc))
        cb.on_epoch_begin(0)
        for step in range(3):
            cb.on_train_batch_begin(step)
            fc.advance(0.5)
            cb.on_train_batch_end(step, {"loss": 1.0 / (step + 1)})
        cb.on_epoch_end(0)
        assert reg.get("train_steps_total").value == 3.0
        assert reg.get("train_tokens_total").value == 768.0
        assert _hist(reg, "train_step_seconds") == (3, pytest.approx(1.5))
        assert reg.get("train_loss").value == pytest.approx(1.0 / 3)
        assert reg.get("train_throughput").value == pytest.approx(512.0)
        (ep,) = [e for e in cb.tracer.events()
                 if e["name"] == "train.epoch"]
        assert ep["dur"] == pytest.approx(1.5e6)

    def test_hapi_fit_integration(self):
        """TelemetryCallback rides Model.fit end to end."""
        from paddle_tpu.hapi.callbacks import TelemetryCallback
        from paddle_tpu.io import TensorDataset
        reg = MetricRegistry()
        x = np.random.RandomState(0).randn(32, 4).astype(np.float32)
        y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
        net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                               pt.nn.Linear(8, 1))
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()),
            loss=pt.nn.BCEWithLogitsLoss())
        model.fit(TensorDataset([x, y]), batch_size=16, epochs=1,
                  verbose=0, shuffle=False,
                  callbacks=[TelemetryCallback(reg, samples_per_batch=16)])
        assert reg.get("train_steps_total").value == 2.0
        assert reg.get("train_samples_total").value == 32.0
        assert reg.get("train_loss").value > 0
        assert reg.get("train_step_seconds").count == 2

    def test_step_timer_bridge(self):
        from paddle_tpu.profiler import StepTimer, profiler_step_timer
        reg = MetricRegistry()
        t = StepTimer().publish_to(reg, prefix="fit_step")
        t.start()
        t.step()
        t.step()
        t.stop()
        h = reg.get("fit_step_seconds")
        # total_time also includes the step2 -> stop() tail segment
        assert h.count == 2 and 0 < h.sum <= t.total_time
        assert reg.get("fit_step_ips").value > 0
        with profiler_step_timer(registry=reg, prefix="loop") as lt:
            lt.step()
            lt.step()
        # start() arms t0, so both steps observe a segment
        assert reg.get("loop_seconds").count == 2

    def test_metric_publish_bridge(self):
        from paddle_tpu.metric import Accuracy, publish
        reg = MetricRegistry()
        acc = Accuracy(topk=(1, 2))
        acc.update(acc.compute(
            np.array([[0.9, 0.05, 0.05], [0.2, 0.7, 0.1]], np.float32),
            np.array([0, 2])))
        publish(acc, reg, name="eval_acc")
        g = reg.get("eval_acc")
        assert g.labels(component="acc_top1").value == 0.5
        assert g.labels(component="acc_top2").value == 0.5


# -------------------------------------------------------------- overhead

class TestDisabledOverheadStructural:
    def test_disabled_instruments_are_allocation_free_singletons(self):
        """The deterministic half of the <2% overhead target (the
        timing half is benchmarks/telemetry_overhead_bench.py): every
        disabled-path operation resolves to the SAME no-op object, and
        a scripted server run performs zero clock reads."""
        reg = MetricRegistry(enabled=False)
        insts = {reg.counter("a"), reg.gauge("b"), reg.histogram("c"),
                 reg.counter("a").labels(x=1)}
        assert insts == {NULL_INSTRUMENT}
        fc = FakeClock()
        tele = ServerTelemetry(registry=reg, clock=fc)
        for _ in range(100):
            t = tele.tick_started()
            tele.on_tick(t, 4, 4)
        assert fc.reads == 0


@pytest.mark.slow
@pytest.mark.bench
class TestEnabledOverheadTiming:
    def test_enabled_decode_tick_overhead_bounded(self):
        """Wall-clock guard for the telemetry bench (target <2% there;
        this CI-variance-tolerant bound only catches order-of-magnitude
        regressions like a lock or sync landing on the tick path)."""
        import time
        from paddle_tpu.inference.continuous_batching import \
            ContinuousBatchingServer
        model = _model()
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (6,)).astype(np.int32)
                   for _ in range(4)]

        def drain(telemetry):
            srv = ContinuousBatchingServer(model, max_slots=4,
                                           max_cache_len=64,
                                           telemetry=telemetry)
            for p in prompts:                    # warm the compiles
                srv.submit(p, max_new_tokens=4)
            srv.run()
            best = float("inf")
            for _ in range(3):
                for p in prompts:
                    srv.submit(p, max_new_tokens=32)
                t0 = time.perf_counter()
                srv.run()
                best = min(best, time.perf_counter() - t0)
            return best

        off = drain(None)
        on = drain(ServerTelemetry())
        assert on < off * 1.5, (on, off)
