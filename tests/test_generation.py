"""model.generate() (models/generation.py): the on-device cached decode
must reproduce the model's own eager forward run token-by-token — the
cache math (GQA, rope offsets, learned positions, tied head) is validated
against the full recompute-every-step loop."""
import numpy as np
import pytest

import paddle_tpu as pt


def _naive_greedy(model, ids_np, n_new):
    """Reference: full forward over the growing sequence each step."""
    ids = ids_np.copy()
    for _ in range(n_new):
        logits = model(pt.to_tensor(ids)).numpy()
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


class TestGreedyParity:
    @pytest.mark.slow
    def test_llama_gqa_generate_matches_eager(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(11)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 256, (2, 5)).astype(np.int32)
        want = _naive_greedy(model, ids, 6)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got.numpy()), want)

    def test_gpt_generate_matches_eager(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(12)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        rng = np.random.default_rng(4)
        ids = rng.integers(0, model.cfg.vocab_size, (2, 4)).astype(np.int32)
        want = _naive_greedy(model, ids, 5)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(got.numpy()), want)

    @pytest.mark.slow
    def test_mixtral_generate_matches_eager(self):
        """MoE decode (dropless dense-expert top-2 combine) must equal
        the eager capacity-dispatch forward at under-capacity loads.
        (slow: two mixtral compiles; server-level mixtral parity stays
        tier-1 in test_continuous_batching/test_paged_attention.)"""
        from paddle_tpu.models.mixtral import (MixtralForCausalLM,
                                               mixtral_tiny)
        pt.seed(31)
        model = MixtralForCausalLM(mixtral_tiny())
        model.eval()
        rng = np.random.default_rng(5)
        ids = rng.integers(0, 256, (2, 4)).astype(np.int32)
        want = _naive_greedy(model, ids, 5)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(got.numpy()), want)

    def test_generate_repeated_call_reuses_programs(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(13)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        ids = np.arange(6, dtype=np.int32).reshape(2, 3)
        a = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                           max_cache_len=32)
        bundle1 = model._pt_decode_cache
        b = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                           max_cache_len=32)
        assert model._pt_decode_cache is bundle1, "bundle rebuilt"
        np.testing.assert_array_equal(a.numpy(), b.numpy())

    def test_gpt_cache_beyond_position_table_refused(self):
        """code-review r5: wpe gathers clamp silently past max_seq_len —
        the builder must refuse oversized caches instead."""
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        model = GPTForCausalLM(gpt2_tiny())
        ids = np.zeros((1, 4), np.int32)
        with pytest.raises(ValueError, match="position table"):
            model.generate(pt.to_tensor(ids), max_new_tokens=4,
                           max_cache_len=model.cfg.max_seq_len + 64)

    def test_generate_length_guard(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        model = LlamaForCausalLM(llama_tiny())
        ids = np.zeros((1, 10), np.int32)
        with pytest.raises(ValueError, match="max_cache_len"):
            model.generate(pt.to_tensor(ids), max_new_tokens=8,
                           max_cache_len=16)


class TestSampling:
    def test_topk1_equals_greedy(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(14)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        ids = np.arange(8, dtype=np.int32).reshape(2, 4)
        greedy = model.generate(pt.to_tensor(ids), max_new_tokens=5)
        sampled = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                                 do_sample=True, top_k=1, seed=0)
        np.testing.assert_array_equal(greedy.numpy(), sampled.numpy())

    def test_same_seed_reproducible_different_seed_varies(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(15)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        ids = np.arange(4, dtype=np.int32).reshape(1, 4)
        kw = dict(max_new_tokens=12, do_sample=True, temperature=3.0)
        a = model.generate(pt.to_tensor(ids), seed=7, **kw)
        b = model.generate(pt.to_tensor(ids), seed=7, **kw)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        outs = [model.generate(pt.to_tensor(ids), seed=s, **kw).numpy()
                for s in range(8, 12)]
        assert any(not np.array_equal(a.numpy(), o) for o in outs), \
            "hot sampling produced identical sequences for 4 other seeds"

    def test_eos_pads_tail(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(16)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        ids = np.zeros((1, 3), np.int32)
        # run greedy once to learn the first generated token, then use it
        # as "eos": everything after the first new token must be eos
        first = model.generate(pt.to_tensor(ids), max_new_tokens=1)
        eos = int(first.numpy()[0, -1])
        out = model.generate(pt.to_tensor(ids), max_new_tokens=6,
                             eos_token_id=eos).numpy()[0]
        assert (out[3:] == eos).all()


class TestQwenVLGenerate:
    @pytest.mark.slow
    def test_vl_generate_matches_eager_joint_forward(self):
        """Multimodal decode: visual prefix in the cache, text decoding
        token-for-token equal to the full joint recompute. (slow: five
        full joint recomputes; text-only VL decode stays tier-1.)"""
        from paddle_tpu.models.qwen_vl import QwenVL, qwen_vl_tiny
        pt.seed(81)
        model = QwenVL(qwen_vl_tiny())
        model.eval()
        rng = np.random.default_rng(17)
        pixels = pt.to_tensor(
            rng.standard_normal((1, 3, 16, 16)).astype("float32"))
        ids = rng.integers(0, 256, (1, 4)).astype(np.int32)

        # naive loop: full joint forward each step, argmax last position
        cur = ids.copy()
        for _ in range(5):
            logits = model(pt.to_tensor(cur), pixels).numpy()
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)

        got = model.generate(pt.to_tensor(ids), pixels, max_new_tokens=5,
                             max_cache_len=64)
        np.testing.assert_array_equal(got.numpy(), cur)

    @pytest.mark.slow
    def test_vl_generate_text_only(self):
        """Without pixels it degrades to plain llama-style decode."""
        from paddle_tpu.models.qwen_vl import QwenVL, qwen_vl_tiny
        pt.seed(82)
        model = QwenVL(qwen_vl_tiny())
        model.eval()
        ids = np.arange(4, dtype=np.int32)[None]
        cur = ids.copy()
        for _ in range(4):
            logits = model(pt.to_tensor(cur)).numpy()
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             max_cache_len=32)
        np.testing.assert_array_equal(got.numpy(), cur)


class TestChunkedPrefill:
    def test_chunked_prefill_matches_whole_prompt(self):
        """Fixed-size prefill chunks (prompt padded up): same tokens as
        the one-shot prefill — padded rows live above the frontier."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(61)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(11)
        ids = rng.integers(0, 256, (2, 7)).astype(np.int32)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                              max_cache_len=64)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                             max_cache_len=64, prefill_chunk=3)
        np.testing.assert_array_equal(got.numpy(), want.numpy())

    def test_chunked_prefill_gpt_positions(self):
        """GPT learned positions must be offset per chunk."""
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(62)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        rng = np.random.default_rng(12)
        ids = rng.integers(0, model.cfg.vocab_size, (1, 5)).astype(
            np.int32)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                              max_cache_len=32)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             max_cache_len=32, prefill_chunk=2)
        np.testing.assert_array_equal(got.numpy(), want.numpy())

    def test_chunk_headroom_guard(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        model = LlamaForCausalLM(llama_tiny())
        ids = np.zeros((1, 13), np.int32)   # pad-to-18 > cache 16
        with pytest.raises(ValueError, match="chunk headroom"):
            model.generate(pt.to_tensor(ids), max_new_tokens=3,
                           max_cache_len=16, prefill_chunk=6)

    def test_server_chunked_prefill_parity(self):
        from paddle_tpu.inference.continuous_batching import (
            ContinuousBatchingServer)
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(63)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (5, 8)]
        srv = ContinuousBatchingServer(model, max_slots=2,
                                       max_cache_len=64,
                                       prefill_chunk=4)
        rids = [srv.submit(p, max_new_tokens=5) for p in prompts]
        outs = srv.run()
        for rid, p in zip(rids, prompts):
            want = model.generate(pt.to_tensor(p[None]),
                                  max_new_tokens=5,
                                  max_cache_len=64).numpy()[0, len(p):]
            np.testing.assert_array_equal(outs[rid], want)


class TestWeightOnlyInt8:
    def test_int8_decode_close_to_fp32(self):
        """Weight-only int8 decode: prefill logits within quantization
        tolerance of fp32, and generation runs end to end."""
        import jax.numpy as jnp

        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(41)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(6)
        ids = rng.integers(0, 256, (2, 5)).astype(np.int32)

        b32 = model._decode_bundle(64, None)
        b8 = model._decode_bundle(64, "int8")
        x0 = model._prefill_embed(jnp.asarray(ids), None)
        out32, _ = b32[2](x0, b32[0](2), jnp.int32(0))
        out8, _ = b8[2](x0, b8[0](2), jnp.int32(0))
        lg32 = np.asarray(b32[3](out32[:, -1:]))
        lg8 = np.asarray(b8[3](out8[:, -1:]))
        rel = (np.abs(lg8 - lg32).max()
               / (np.abs(lg32).max() + 1e-9))
        assert rel < 0.05, f"int8 drift too large: {rel}"

        out = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             weight_dtype="int8", max_cache_len=64)
        assert out.numpy().shape == (2, 9)

    def test_int8_bundle_cached_separately(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(42)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        ids = np.zeros((1, 3), np.int32)
        a = model.generate(pt.to_tensor(ids), max_new_tokens=3,
                           max_cache_len=32)
        b = model.generate(pt.to_tensor(ids), max_new_tokens=3,
                           weight_dtype="int8", max_cache_len=32)
        c = model.generate(pt.to_tensor(ids), max_new_tokens=3,
                           max_cache_len=32)
        # fp32 results stable across the interleaved int8 call
        np.testing.assert_array_equal(a.numpy(), c.numpy())


class TestBf16Generate:
    @pytest.mark.skipif(
        tuple(int(x) for x in __import__("jax").__version__
              .split(".")[:2]) < (0, 5),
        reason="bf16 eager-vs-decode exact tokens hit a sub-ulp top-2 "
               "tie (gap 0.008 at the divergence step) that this older "
               "XLA CPU rounds the other way; f32 parity and all server "
               "parity suites still assert exact tokens")
    def test_bf16_model_generate_matches_bf16_eager(self):
        """The serving dtype on TPU is bf16: decode parity must hold
        against the model's own bf16 eager forward."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(45)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        model.astype("bfloat16")
        rng = np.random.default_rng(25)
        ids = rng.integers(0, 256, (1, 5)).astype(np.int32)
        want = _naive_greedy(model, ids, 5)
        got = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                             max_cache_len=32)
        np.testing.assert_array_equal(np.asarray(got.numpy()), want)


class TestInt8KVCache:
    def test_int8_kv_close_to_fp_and_actually_int8(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(43)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(19)
        ids = rng.integers(0, 256, (2, 6)).astype(np.int32)

        bfp = model._decode_bundle(32)
        b8 = model._decode_bundle(32, cache_dtype="int8")
        caches8 = b8[0](2)
        assert caches8["k"].dtype == jnp.int8 and "ks" in caches8
        x0 = model._prefill_embed(jnp.asarray(ids), None)
        outf, _ = bfp[2](x0, bfp[0](2), jnp.int32(0))
        out8, _ = b8[2](x0, b8[0](2), jnp.int32(0))
        lf = np.asarray(bfp[3](outf[:, -1:]))
        l8 = np.asarray(b8[3](out8[:, -1:]))
        rel = np.abs(l8 - lf).max() / (np.abs(lf).max() + 1e-9)
        assert rel < 0.05, f"int8 KV drift too large: {rel}"

        out = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             max_cache_len=32, cache_dtype="int8")
        assert out.numpy().shape == (2, 10)

    def test_int8_kv_through_server_parity(self):
        from paddle_tpu.inference.continuous_batching import (
            ContinuousBatchingServer)
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(44)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        rng = np.random.default_rng(21)
        p = rng.integers(0, model.cfg.vocab_size, (5,)).astype(np.int32)
        want = model.generate(pt.to_tensor(p[None]), max_new_tokens=4,
                              max_cache_len=32,
                              cache_dtype="int8").numpy()[0, 5:]
        srv = ContinuousBatchingServer(model, max_slots=1,
                                       max_cache_len=32,
                                       cache_dtype="int8")
        rid = srv.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(srv.run()[rid], want)


def test_process_logits_filters():
    import jax.numpy as jnp

    from paddle_tpu.inference.decode_loop import process_logits
    logits = jnp.asarray([[1.0, 3.0, 2.0, -1.0]])
    k2 = np.asarray(process_logits(logits, top_k=2))
    assert k2[0, 1] == 3.0 and k2[0, 2] == 2.0
    assert k2[0, 0] < -1e20 and k2[0, 3] < -1e20
    # top_p tiny: only the argmax survives
    p = np.asarray(process_logits(logits, top_p=1e-6))
    assert p[0, 1] == 3.0 and (p[0, [0, 2, 3]] < -1e20).all()
    # temperature scales
    t = np.asarray(process_logits(logits, temperature=2.0))
    np.testing.assert_allclose(t[0], [0.5, 1.5, 1.0, -0.5])
