"""RPC + parameter-server tests — real multi-process, mirroring the
reference's single-host multi-process pattern (test_rpc_*.py,
test_dist_fleet_ps*.py)."""
import multiprocessing as mp
import os
import socket
import time

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _env():
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    return env


# ------------------------------------------------------------------- rpc
def _sq(x):
    return x * x


def _boom():
    raise ValueError("remote boom")


def _rpc_worker(rank, world, port, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.parallel import rpc
    rpc.init_rpc(f"w{rank}", rank=rank, world_size=world,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        if rank == 0:
            assert rpc.rpc_sync("w1", _sq, args=(7,)) == 49
            fut = rpc.rpc_async("w1", _sq, args=(np.arange(3),))
            np.testing.assert_array_equal(fut.wait(), [0, 1, 4])
            try:
                rpc.rpc_sync("w1", _boom)
                q.put(("fail", "no exception"))
                return
            except ValueError as e:
                assert "remote boom" in str(e)
            infos = rpc.get_all_worker_infos()
            assert [i.name for i in infos] == ["w0", "w1"]
            assert rpc.get_worker_info("w1").rank == 1
            q.put(("ok", rank))
        else:
            # server side just stays alive until shutdown barrier
            q.put(("ok", rank))
    finally:
        rpc.shutdown()


def test_rpc_two_processes():
    ctx = mp.get_context("spawn")
    port = _free_port()
    q = ctx.Queue()
    procs = [ctx.Process(target=_rpc_worker, args=(r, 2, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = [q.get(timeout=90) for _ in procs]
    for p in procs:
        p.join(timeout=90)
    assert all(s == "ok" for s, _ in results), results


# -------------------------------------------------------------------- ps
def _ps_proc(role, index, n_srv, n_wrk, port, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.parallel import rpc
    from paddle_tpu.parallel.ps import TheOnePSRuntime
    rt = TheOnePSRuntime(role=role, index=index, num_servers=n_srv,
                         num_workers=n_wrk,
                         master_endpoint=f"127.0.0.1:{port}").init()
    try:
        if role == "PSERVER":
            q.put(("ok", f"s{index}"))
            rt.run_server()
        else:
            c = rt.client
            c.create_table("emb", dim=4, initializer="zeros", lr=0.5)
            ids = np.array([1, 2, 5, 2])
            rows = c.pull_sparse("emb", ids)
            assert rows.shape == (4, 4)
            np.testing.assert_allclose(rows, 0)  # zero init
            # push grad of ones for ids [1,2]; server applies -lr*g
            c.push_sparse("emb", np.array([1, 2]), np.ones((2, 4)))
            after = c.pull_sparse("emb", np.array([1, 2, 5]))
            np.testing.assert_allclose(after[0], -0.5)
            np.testing.assert_allclose(after[1], -0.5)
            np.testing.assert_allclose(after[2], 0.0)
            st = c.save_table("emb")
            assert set(st["ids"].tolist()) == {1, 2, 5}
            q.put(("ok", f"w{index}"))
    except Exception as e:  # pragma: no cover
        q.put(("fail", f"{role}{index}: {e!r}"))
    finally:
        rt.stop()


def test_parameter_server_end_to_end():
    ctx = mp.get_context("spawn")
    port = _free_port()
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_ps_proc, args=("PSERVER", 0, 2, 1, port, q)),
        ctx.Process(target=_ps_proc, args=("PSERVER", 1, 2, 1, port, q)),
        ctx.Process(target=_ps_proc, args=("TRAINER", 0, 2, 1, port, q)),
    ]
    for p in procs:
        p.start()
    results = [q.get(timeout=120) for _ in procs]
    for p in procs:
        p.join(timeout=120)
        if p.is_alive():
            p.terminate()
    assert all(s == "ok" for s, _ in results), results


def test_sparse_table_local():
    from paddle_tpu.parallel.ps import SparseTable
    t = SparseTable("t", dim=3, initializer="uniform", lr=1.0)
    r = t.pull([4, 9])
    assert r.shape == (2, 3)
    before = r.copy()
    t.push_grad([4], np.ones((1, 3)))
    after = t.pull([4])
    np.testing.assert_allclose(after[0], before[0] - 1.0, rtol=1e-6)


def _fleet_ps_proc(role, index, n_srv, n_wrk, port, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["TRAINING_ROLE"] = role
    os.environ["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(
        f"127.0.0.1:{7000+i}" for i in range(n_srv))
    os.environ["PADDLE_TRAINERS_NUM"] = str(n_wrk)
    os.environ["PADDLE_MASTER_ENDPOINT"] = f"127.0.0.1:{port}"
    if role == "PSERVER":
        os.environ["PADDLE_PSERVER_ID"] = str(index)
    else:
        os.environ["PADDLE_TRAINER_ID"] = str(index)
    from paddle_tpu.parallel import fleet as fleet_mod
    fleet = fleet_mod.fleet
    fleet.init(is_collective=False)
    try:
        if fleet.is_server():
            fleet.init_server()
            q.put(("ok", f"s{index}"))
            fleet.run_server()
        else:
            fleet.init_worker()
            c = fleet._ps_runtime.client
            c.create_table("emb", dim=2, initializer="zeros", lr=1.0)
            c.push_sparse("emb", np.array([3]), np.ones((1, 2)))
            row = c.pull_sparse("emb", np.array([3]))
            np.testing.assert_allclose(row[0], -1.0)
            q.put(("ok", f"w{index}"))
    except Exception as e:  # pragma: no cover
        q.put(("fail", f"{role}{index}: {e!r}"))
    finally:
        fleet.stop_worker()


def test_fleet_ps_mode():
    ctx = mp.get_context("spawn")
    port = _free_port()
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_fleet_ps_proc,
                    args=("PSERVER", 0, 1, 1, port, q)),
        ctx.Process(target=_fleet_ps_proc,
                    args=("TRAINER", 0, 1, 1, port, q)),
    ]
    for p in procs:
        p.start()
    results = [q.get(timeout=120) for _ in procs]
    for p in procs:
        p.join(timeout=120)
        if p.is_alive():
            p.terminate()
    assert all(s == "ok" for s, _ in results), results


def test_fleet_stop_worker_safe_without_ps():
    from paddle_tpu.parallel import fleet as fleet_mod
    f = fleet_mod._Fleet()
    f.stop_worker()  # must be a no-op, not AttributeError
    f.run_server()
    f.init_worker()


def _unpicklable():
    return lambda: None  # locals in a lambda aren't picklable by name


def _rpc_worker2(rank, world, port, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.parallel import rpc
    rpc.init_rpc(f"w{rank}", rank=rank, world_size=world,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        if rank == 0:
            # unpicklable result must come back as a prompt RuntimeError,
            # not a hung socket timeout
            t0 = time.time()
            try:
                rpc.rpc_sync("w1", _unpicklable, timeout=60)
                q.put(("fail", "no error for unpicklable result"))
                return
            except RuntimeError as e:
                assert "not picklable" in str(e), str(e)
            assert time.time() - t0 < 30, "should fail fast, not time out"
            # persistent connection: many calls reuse one socket happily
            for i in range(20):
                assert rpc.rpc_sync("w1", _sq, args=(i,)) == i * i
            q.put(("ok", rank))
        else:
            q.put(("ok", rank))
    finally:
        rpc.shutdown()


def test_rpc_unpicklable_and_persistent_conns():
    ctx = mp.get_context("spawn")
    port = _free_port()
    q = ctx.Queue()
    procs = [ctx.Process(target=_rpc_worker2, args=(r, 2, port, q))
             for r in range(2)]
    for p in procs:
        p.start()
    results = [q.get(timeout=90) for _ in procs]
    for p in procs:
        p.join(timeout=90)
    assert all(s == "ok" for s, _ in results), results
