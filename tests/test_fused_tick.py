"""Fused mixed prefill/decode megakernel — one launch per serving tick
(ISSUE 14).

Four layers of coverage:

- Schedule + kernel: ``build_schedule`` lists exactly the live pages
  slot-major with a quarter-octave sentinel pad; the Pallas fused-tick
  kernel
  (interpret mode) matches the gather reference on mixed phases —
  prefill chunks at prefix offsets, single decode rows, idle slots —
  skips idle slots to exact zeros, and never reads past a row's causal
  frontier. The XLA reference's masked softmax is BITWISE invariant to
  the gathered frame's extent (the platform assumption the server's
  live-width pow2 ladder rides; if this ever fails, the ladder must be
  pinned to the full table width).
- Server parity: ``serving_mode="fused"`` emits tokens bit-identical
  to the split ragged path AND the dense backend (greedy + seeded
  sampling, mixed lengths, chunk-straddling budgets, auto-hit
  resumes).
- Dispatch profile (acceptance): steady-state AND admission ticks
  dispatch exactly once — every recorder tick event's per-op histogram
  is ``{"fused": 1}`` — where the split path's admission ticks issue
  prefill + state_push + block_table dispatches on top of decode.
- Lifecycle + ledgers: mid-prefill cancel/deadline tear down leak-free;
  optimistic-admission preemption replays bit-exactly through the
  fused path; the goodput ledger charges NO null_redirect and only the
  schedule's ladder pad as ``skipped_page_dma`` (the PR-6/PR-10 cut,
  lifted); the cost catalog prices the fused program and its compiled
  bytes are (near-)flat in the CONFIGURED block-table width; tick
  phases attribute ``fused_launch``.
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.ops.pallas import fused_tick as ft


def _rand(*shape, seed=0, scale=0.5):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)


@functools.lru_cache(maxsize=1)
def _model():
    # one llama across the module (the test_ragged_prefill pattern):
    # every parity test shares the same (max_cache_len, page_size)
    # bundles, so the compiles are paid once
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _stub_srv(**kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 32)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 4)
    kw.setdefault("serving_mode", "fused")
    return ContinuousBatchingServer(StubModel(), **kw)


# ------------------------------------------------------------- schedule


class TestSchedule:
    def test_live_pages_slot_major_with_pow2_pad(self):
        ss, sp, n_live = ft.build_schedule([7, -1, 0, 8], 4, n_slots=4)
        # slot 0: pages 0,1; slot 1 idle; slot 2: page 0; slot 3: 0..2
        want_s = [0, 0, 2, 3, 3, 3]
        want_p = [0, 1, 0, 0, 1, 2]
        assert n_live == 6
        assert len(ss) == 8                     # pow2 (min_entries=8)
        np.testing.assert_array_equal(ss[:6], want_s)
        np.testing.assert_array_equal(sp[:6], want_p)
        np.testing.assert_array_equal(ss[6:], [4, 4])   # sentinel pad
        np.testing.assert_array_equal(sp[6:], [0, 0])

    def test_all_idle_and_ladder_growth(self):
        ss, sp, n_live = ft.build_schedule([-1, -1], 4)
        assert n_live == 0 and len(ss) == 8 and (np.asarray(ss) == 2).all()
        # quarter-octave ladder: 9 live pages pads to 10 (not pow2 16)
        ss2, _, n2 = ft.build_schedule([4 * 9 - 1], 4)
        assert n2 == 9 and len(ss2) == 10
        # pad never exceeds ~25% of the live entries past min_entries
        for n in (9, 33, 67, 129, 257, 511):
            total = ft._ladder(n, 8)
            assert n <= total <= n + max(1, n // 4)


# --------------------------------------------------------------- kernel


class TestFusedTickKernel:
    S, C, nh, kvh, hd, P, pg, W = 3, 4, 4, 2, 16, 12, 4, 4

    def _mixed(self, seed=1):
        """Slot 0: cold prefill chunk (4 rows). Slot 1: one decode row
        at t=9. Slot 2: idle."""
        q = _rand(self.S, self.C, self.nh, self.hd, seed=seed)
        kp = _rand(self.P, self.pg, self.kvh, self.hd, seed=seed + 1)
        vp = _rand(self.P, self.pg, self.kvh, self.hd, seed=seed + 2)
        rng = np.random.RandomState(seed + 3)
        bt = jnp.asarray(np.stack([
            rng.choice(np.arange(1, self.P), self.W, replace=False)
            for _ in range(self.S)]).astype(np.int32))
        t0 = jnp.asarray(np.array([0, 9, 0], np.int32))
        last = jnp.asarray(np.array([3, 9, -1], np.int32))
        dec = jnp.asarray(np.array([0, 1, 0], np.int32))
        ss, sp, _ = ft.build_schedule(np.asarray(last), self.pg,
                                      n_slots=self.S)
        return q, kp, vp, bt, t0, last, dec, jnp.asarray(ss), \
            jnp.asarray(sp)

    def test_kernel_matches_gather_oracle_mixed_phases(self):
        q, kp, vp, bt, t0, last, dec, ss, sp = self._mixed()
        out = ft.fused_tick_attention(q, kp, vp, bt, t0, last, dec,
                                      ss, sp, sm_scale=0.25,
                                      interpret=True)
        ref = ft._ref_fused_tick(q, kp, vp, bt, t0, dec, 0.25)
        # prefill slot: all 4 live rows; decode slot: row 0 only
        np.testing.assert_allclose(np.asarray(out)[0],
                                   np.asarray(ref)[0],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(out)[1, 0],
                                   np.asarray(ref)[1, 0],
                                   rtol=2e-5, atol=2e-5)
        # idle slot reads as exact zeros on BOTH paths
        assert (np.asarray(out)[2] == 0).all()

    def test_kernel_never_reads_past_causal_frontier(self):
        """Poisoning every pool row beyond the slots' frontiers must
        not change one output bit — the schedule + mask are the proof
        the kernel's page traffic stops at the live frontier."""
        q, kp, vp, bt, t0, last, dec, ss, sp = self._mixed(seed=7)
        out1 = ft.fused_tick_attention(q, kp, vp, bt, t0, last, dec,
                                       ss, sp, sm_scale=0.3,
                                       interpret=True)
        # slot 0 sees positions <= 3 (page bt[0,0]); slot 1 sees
        # <= 9 (pages bt[1,0..2], row 1 of page 2). Poison everything
        # else, including all of idle slot 2's pages.
        touched = set(np.asarray(bt)[0, :1]) | set(np.asarray(bt)[1, :3])
        kp2, vp2 = kp, vp
        for pid in range(self.P):
            if pid not in touched:
                kp2 = kp2.at[pid].set(1e3)
                vp2 = vp2.at[pid].set(-1e3)
        kp2 = kp2.at[int(np.asarray(bt)[1, 2]), 2:].set(1e3)
        vp2 = vp2.at[int(np.asarray(bt)[1, 2]), 2:].set(-1e3)
        out2 = ft.fused_tick_attention(q, kp2, vp2, bt, t0, last, dec,
                                       ss, sp, sm_scale=0.3,
                                       interpret=True)
        np.testing.assert_array_equal(np.asarray(out1)[0],
                                      np.asarray(out2)[0])
        np.testing.assert_array_equal(np.asarray(out1)[1, 0],
                                      np.asarray(out2)[1, 0])

    def test_prefill_rows_bitwise_invariant_to_gathered_extent(self):
        """Prefill rows through the reference path must not move a bit
        when the gathered frame widens past every causal frontier (the
        multi-row einsum is extent-stable on this XLA; decode rows are
        pinned at the MODEL level below — a bare attention-only
        program's s=1 reduce is shape-lucky at tiny head dims)."""
        q, kp, vp, bt, t0, last, dec, _, _ = self._mixed(seed=11)
        narrow = ft._ref_fused_tick(q, kp, vp, bt[:, :3], t0, dec, 0.25)
        wide = ft._ref_fused_tick(q, kp, vp, bt, t0, dec, 0.25)
        np.testing.assert_array_equal(np.asarray(narrow)[0],
                                      np.asarray(wide)[0])

    def test_model_decode_row_bitwise_invariant_to_table_width(self):
        """THE platform assumption under the server's live-width pow2
        ladder: the whole-model fused program's decode-row logits are
        bitwise identical to the split s=1 decode program at EVERY
        ladder width W, so the same request decodes the same tokens
        whichever width its tick happens to ride. (If this ever fails
        on a new XLA, pin the fused launch to the full table width.)"""
        m = _model()
        MCL, PG, NP, S = 64, 8, 33, 2
        b = m._decode_bundle(MCL, cache_backend="paged", page_size=PG,
                             num_pages=NP)
        init_p, embed_fn, head_fn = b[0], b[1], b[3]
        step_jit, ragged_jit, fused_fn = b[4], b[5], b[6]
        fused_jit = jax.jit(fused_fn)
        copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 256, (12,)).astype(np.int32)
        caches = init_p(S)
        bt = np.zeros((S, MCL // PG), np.int32)
        bt[0, :3] = [1, 2, 3]
        bt[1, :3] = [4, 5, 6]
        caches = dict(caches, bt=jnp.asarray(bt))
        toks = np.zeros((S, 16), np.int32)
        toks[0, :12] = ids
        lg, caches = ragged_jit(
            jnp.asarray(toks), jnp.asarray(np.array([0, MCL], np.int32)),
            caches, jnp.asarray(np.array([11, 0], np.int32)))
        nxt = int(np.argmax(np.asarray(lg)[0]))

        t_dev = jnp.asarray(np.array([12, MCL], np.int32))
        x = embed_fn(jnp.asarray(np.array([nxt, 0], np.int32)), t_dev)
        out, _ = step_jit(x, copy(caches), t_dev)
        lg_split = np.asarray(head_fn(out[:, -1:, :])[:, -1])[0]

        last = np.array([12, -1], np.int32)
        ss, sp, _ = ft.build_schedule(last, PG, n_slots=S)
        toks_f = np.zeros((S, 2), np.int32)
        toks_f[0, 0] = nxt
        for W in (2, 4, 8):
            lg_f, _ = fused_jit(
                jnp.asarray(toks_f), t_dev, jnp.asarray(last),
                jnp.asarray(np.array([1, 0], np.int32)), copy(caches),
                jnp.asarray(np.zeros(S, np.int32)),
                jnp.asarray(np.ascontiguousarray(bt[:, :W])),
                jnp.asarray(ss), jnp.asarray(sp))
            np.testing.assert_array_equal(np.asarray(lg_f)[0], lg_split)


# -------------------------------------------------------- server parity


class TestFusedServerParity:
    def _three_way(self, model, prompts, n_new, budget=None,
                   seeds=None, **kw):
        """dense backend vs split ragged vs FUSED: bit-identical
        per-request tokens. Returns the fused server."""
        if seeds is None:
            seeds = list(range(100, 100 + len(prompts)))
        outs, servers = [], []
        for mode_kw in ({"cache_backend": "dense"},
                        {"cache_backend": "paged", "page_size": 8,
                         "prefill_mode": "ragged",
                         "prefill_tokens_per_tick": budget},
                        {"cache_backend": "paged", "page_size": 8,
                         "prefill_mode": "ragged",
                         "serving_mode": "fused",
                         "prefill_tokens_per_tick": budget}):
            srv = ContinuousBatchingServer(model, max_slots=2,
                                           max_cache_len=64,
                                           **mode_kw, **kw)
            rids = [srv.submit(p, max_new_tokens=n_new, seed=s)
                    for p, s in zip(prompts, seeds)]
            res = srv.run()
            outs.append([res[r] for r in rids])
            servers.append(srv)
        for got_split, got_fused, got_dense in zip(outs[1], outs[2],
                                                   outs[0]):
            np.testing.assert_array_equal(got_split, got_dense)
            np.testing.assert_array_equal(got_fused, got_dense)
        return servers[2]

    @pytest.mark.slow
    def test_greedy_parity_mixed_lengths(self):
        """Mixed prompt lengths 1 / pg-1 / pg / multi-page — 5 requests
        through 2 slots (refill mid-run), fused vs split vs dense all
        bit-identical, pool returned clean. (slow: 3 servers x 5
        requests; chunk-straddling keeps three-way parity tier-1.)"""
        model = _model()
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (1, 7, 8, 12, 17)]
        srv = self._three_way(model, prompts, 6)
        assert srv.serving_mode == "fused"
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0

    def test_greedy_parity_chunk_straddling_budget(self):
        """A 4-token budget slices prompts across ticks at arbitrary
        cut points; mid-prefill slots are REAL prefill rows in the
        fused launch and tokens must not move a bit."""
        model = _model()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (9, 13, 5)]
        self._three_way(model, prompts, 5, budget=4)

    @pytest.mark.slow
    def test_sampled_parity_seeded(self):
        """The in-program sampling epilogue (PRNG keys riding the
        launch as arguments) replays the host-eager chains exactly.
        (slow: extreme-seeds keeps the sampled epilogue tier-1.)"""
        model = _model()
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (4, 11, 6)]
        self._three_way(model, prompts, 6, do_sample=True,
                        temperature=1.3, top_k=9)

    @pytest.mark.slow
    def test_sampled_parity_extreme_seeds(self):
        """Seeds with bit 31 set (and negative ones) must pack into
        the launch's int32 seed row by two's-complement wrap — NumPy 2
        raises on a bare np.int32(big) — and still replay the host
        PRNGKey chain bit-exactly."""
        model = _model()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 256, (n,)).astype(np.int32)
                   for n in (5, 9, 7)]
        self._three_way(model, prompts, 5,
                        seeds=[2**31, -1, 2**32 - 2],
                        do_sample=True, temperature=0.9, top_k=11)

    def test_auto_hit_resume_parity(self):
        """A second identical prompt auto-hits the prefix cache and
        resumes its remainder chunk at t0 > 0 inside the fused launch
        — tokens bit-match the cold run."""
        srv = _stub_srv(max_slots=1, max_cache_len=48)
        p = (np.arange(13, dtype=np.int32) * 3) % 16
        ra = srv.submit(p, max_new_tokens=5)
        out_a = srv.run()[ra]
        rb = srv.submit(p, max_new_tokens=5)
        out_b = srv.run()[rb]
        np.testing.assert_array_equal(out_a, stub_tokens(p, 5))
        np.testing.assert_array_equal(out_b, out_a)
        assert srv.stats["prefix_auto_hits"] == 1


# -------------------------------------------- dispatch profile acceptance


class TestFusedDispatchProfile:
    def _tick_profiles(self, serving_mode):
        from paddle_tpu.telemetry import FlightRecorder
        rec = FlightRecorder()
        srv = ContinuousBatchingServer(
            StubModel(), max_slots=3, max_cache_len=48,
            cache_backend="paged", page_size=4, recorder=rec,
            serving_mode=serving_mode, prefill_tokens_per_tick=6)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 99, (n,)).astype(np.int32)
                   for n in (5, 11, 3, 9, 2)]
        rids = [srv.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, (6, 3, 9, 4, 2))]
        outs = srv.run()
        for rid, p, b in zip(rids, prompts, (6, 3, 9, 4, 2)):
            np.testing.assert_array_equal(outs[rid], stub_tokens(p, b))
        return [e["dispatches"] for e in rec.events()
                if e["kind"] == "tick" and e["dispatches"]]

    def test_every_tick_is_one_fused_dispatch(self):
        """ACCEPTANCE (ISSUE 14): steady-state AND admission ticks —
        slot refills mid-run included — show the per-op dispatch
        histogram collapsed to {"fused": 1}, where the split path's
        admission ticks issue prefill/state_push/block_table dispatches
        on top of decode."""
        fused = self._tick_profiles("fused")
        assert fused and all(d == {"fused": 1} for d in fused), fused
        split = self._tick_profiles("split")
        assert any(len(d) > 1 or "prefill" in d for d in split), \
            "split baseline lost its admission dispatches — " \
            "the comparison is vacuous"
        assert max(sum(d.values()) for d in split) > 1

    def test_stats_count_one_dispatch_per_tick(self):
        srv = _stub_srv()
        rid = srv.submit(np.arange(6, dtype=np.int32), max_new_tokens=4)
        srv.step()                    # admission tick: prefill + first
        assert srv.stats["tick_dispatches"] == 1
        assert srv.stats["prefill_dispatches"] == 1
        srv.step()                    # steady-state decode tick
        assert srv.stats["tick_dispatches"] == 2
        assert srv.stats["prefill_dispatches"] == 1   # no new admission
        out = srv.run()
        np.testing.assert_array_equal(
            out[rid], stub_tokens(np.arange(6, dtype=np.int32), 4))


# ----------------------------------------------------- lifecycle + replay


class TestFusedLifecycle:
    def test_cancel_and_deadline_mid_prefill_leak_free(self):
        from paddle_tpu.telemetry.clock import FakeClock
        fc = FakeClock()
        srv = _stub_srv(max_slots=1, prefill_tokens_per_tick=2,
                        clock=fc)
        usable = srv._kv.num_pages - 1
        long_p = (np.arange(20, dtype=np.int32) * 5) % 16
        ra = srv.submit(long_p, max_new_tokens=4)
        srv.step()                               # mid-prefill
        st = next(s for s in srv._slots if s is not None)
        assert st.phase == "prefill"
        assert srv.cancel(ra) is True
        assert np.asarray(srv._results[ra]).size == 0
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and free + cached == usable

        rb = srv.submit(long_p, max_new_tokens=4, deadline_s=5.0)
        srv.step()
        fc.advance(10.0)                         # expire mid-prefill
        srv.step()
        free, live, pinned, cached = srv.pool_balance()
        assert live == 0 and free + cached == usable
        assert np.asarray(srv._results[rb]).size == 0
        rc = srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=3)
        np.testing.assert_array_equal(
            srv.run()[rc], stub_tokens(np.arange(4, dtype=np.int32), 3))

    def test_decode_never_starved_by_long_prefill(self):
        """The split scheduler's starvation invariant carries over:
        while a long prompt streams in under a small budget, an
        in-flight decode slot advances EVERY tick (its row rides the
        same launch)."""
        srv = _stub_srv(max_slots=2, prefill_tokens_per_tick=3)
        a = np.arange(3, dtype=np.int32)
        ra = srv.submit(a, max_new_tokens=20)
        srv.step()
        st_a = next(s for s in srv._slots if s is not None)
        assert srv._active.any()
        b = (np.arange(24, dtype=np.int32) * 3) % 16
        rb = srv.submit(b, max_new_tokens=4)
        guard = 0
        while any(s is not None and s.phase == "prefill"
                  for s in srv._slots) or srv._queue:
            before = len(st_a.emitted)
            srv.step()
            guard += 1
            assert len(st_a.emitted) == before + 1, \
                "in-flight decode starved by fused prefill rows"
            assert guard < 50
        outs = srv.run()
        np.testing.assert_array_equal(outs[rb], stub_tokens(b, 4))
        np.testing.assert_array_equal(outs[ra], stub_tokens(a, 20))

    @pytest.mark.parametrize(
        "do_sample",
        [False,
         # sampled variant is slow-marked: the sampling epilogue adds a
         # second pair of compiles; greedy keeps the replay contract
         # tier-1
         pytest.param(True, marks=pytest.mark.slow)])
    def test_preemption_replay_bit_exact(self, do_sample):
        """Optimistic admission under a pool ~2.5x too small: victims
        park and REPLAY through the fused path bit-exactly vs an
        unpressured reserve run (greedy and seeded-sampled), zero
        leaks."""
        from paddle_tpu.reliability import CircuitBreaker, RetryPolicy

        def run(admission, num_pages):
            srv = ContinuousBatchingServer(
                StubModel(), max_slots=4, max_cache_len=64,
                cache_backend="paged", page_size=8,
                num_pages=num_pages, admission=admission,
                serving_mode="fused", do_sample=do_sample, seed=5,
                retry_policy=RetryPolicy(base_delay_s=0.0, jitter=0.0),
                breaker=CircuitBreaker(failure_threshold=10_000))
            rng = np.random.default_rng(3)
            prompts = [rng.integers(0, 16, (int(n),)).astype(np.int32)
                       for n in rng.integers(3, 12, 10)]
            rids = [srv.submit(p, max_new_tokens=28, seed=100 + i)
                    for i, p in enumerate(prompts)]
            outs = srv.run()
            return srv, prompts, [outs[r] for r in rids]

        srv, prompts, outs = run("optimistic", 9)
        _, _, outs2 = run("reserve", 49)
        assert srv.stats["preemptions"] > 0, "pool never pressured"
        for a, b in zip(outs, outs2):
            np.testing.assert_array_equal(a, b)
        if not do_sample:
            for p, a in zip(prompts, outs):
                np.testing.assert_array_equal(a, stub_tokens(p, 28))
        bal = srv.pool_balance()
        assert bal[1] == 0 and bal.preempted == 0


# -------------------------------------------------- ledgers + cost + phase


class TestFusedLedgers:
    def _workload(self, serving_mode, **kw):
        from paddle_tpu.telemetry import GoodputLedger
        led = GoodputLedger()
        # 64-wide block tables: the split kernels' full-width masked
        # DMA dominates here exactly like the bench's 0.001 baseline
        srv = ContinuousBatchingServer(
            StubModel(), max_slots=3, max_cache_len=256,
            cache_backend="paged", page_size=4, ledger=led,
            serving_mode=serving_mode, prefill_tokens_per_tick=6, **kw)
        rng = np.random.default_rng(0)
        for n, b in ((9, 6), (5, 8), (13, 4), (3, 7)):
            srv.submit(rng.integers(0, 99, (n,)).astype(np.int32),
                       max_new_tokens=b)
        srv.run()
        return led.snapshot()

    def test_goodput_no_null_redirect_and_pad_only_dma(self):
        """The two waste kinds ISSUE 14 exists to kill: fused ticks
        charge ZERO null_redirect (mid-prefill slots are real prefill
        rows, idle slots are kernel-skipped) and skipped_page_dma
        collapses from the split kernels' full-table-width model to
        the schedule's quarter-octave ladder pad — the goodput ratio improves by
        well over the 10x acceptance bar on the same workload."""
        split = self._workload("split")
        fused = self._workload("fused")
        assert fused["tokens"].get("null_redirect", 0) == 0
        assert split["tokens"]["null_redirect"] > 0
        dma_f = fused["tokens"].get("skipped_page_dma", 0)
        dma_s = split["tokens"]["skipped_page_dma"]
        assert dma_f < dma_s / 5, (dma_f, dma_s)
        assert fused["goodput_ratio"] >= 10 * split["goodput_ratio"], \
            (fused["goodput_ratio"], split["goodput_ratio"])
        # both modes did the same useful work
        assert fused["tokens"]["goodput"] == split["tokens"]["goodput"]

    def test_costs_price_fused_program_and_phases(self):
        """Satellites: the fused program is priced through
        ``CostCatalog.program()`` (charged per dispatch, compile
        counted under op="fused") and tick-phase attribution survives
        the fused path — ``fused_launch`` carries the launch wall,
        ``last_tick_phases`` stays meaningful."""
        from paddle_tpu.telemetry import CostCatalog
        cat = CostCatalog()
        srv = _stub_srv(costs=cat)
        rid = srv.submit(np.arange(7, dtype=np.int32), max_new_tokens=5)
        out = srv.run()
        np.testing.assert_array_equal(
            out[rid], stub_tokens(np.arange(7, dtype=np.int32), 5))
        snap = cat.snapshot()
        assert cat.compiles().get("fused", 0) >= 1
        fused = snap["ops"]["fused"]
        assert fused["dispatches"] >= 5 and fused["hbm_bytes"] > 0
        # one program per (C, W, G) ladder point, cached host-side
        assert len(srv._fused_progs) == len(
            {k for k in srv._fused_progs})
        phases = snap["last_tick_phases"]
        assert "fused_launch" in phases
        assert "decode_launch" not in phases and \
            "prefill_launch" not in phases

    def test_fused_bytes_flat_in_configured_table_width(self):
        """Satellite (the direct proof the skipped-page DMA is gone):
        the fused program's compiled cost-analysis bytes are
        (near-)flat in the CONFIGURED block-table width for fixed live
        pages — the launch takes the LIVE slice, so a 4x wider table
        prices the same. The split kernels' bytes are AFFINE in that
        width with positive slope (tests/test_costs.py pins the
        slope), which is exactly the waste this kernel deletes."""
        from paddle_tpu.telemetry import CostCatalog

        def fused_bytes(max_cache_len):
            cat = CostCatalog()
            m = _model()
            # num_pages PINNED: cost-analysis bytes count the whole
            # K/V pool buffer, so only the table width may vary
            srv = ContinuousBatchingServer(
                m, max_slots=2, max_cache_len=max_cache_len,
                cache_backend="paged", page_size=8, num_pages=40,
                serving_mode="fused", costs=cat)
            rng = np.random.default_rng(5)
            for n in (5, 9):
                srv.submit(rng.integers(0, 256, (n,)).astype(np.int32),
                           max_new_tokens=3)
            srv.run()
            ops = cat.snapshot()["ops"]["fused"]
            return ops["hbm_bytes"] / ops["dispatches"]

        b_narrow = fused_bytes(64)        # 8-page tables
        b_wide = fused_bytes(256)         # 32-page tables, same work
        assert abs(b_wide - b_narrow) / b_narrow < 0.10, \
            f"fused bytes moved {b_narrow:.0f} -> {b_wide:.0f} " \
            f"with table width — the live-slice contract broke"


# --------------------------------------------------------- config guards


class TestFusedConfigGuards:
    def test_serving_mode_validation(self):
        with pytest.raises(ValueError, match="serving_mode"):
            _stub_srv(serving_mode="bogus")
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatchingServer(StubModel(), max_cache_len=32,
                                     serving_mode="fused")
        with pytest.raises(ValueError, match="ragged"):
            _stub_srv(prefill_mode="dense")
        srv = _stub_srv(serving_mode="split")
        assert srv.serving_mode == "split" and not srv._fused

    def test_tick_block_gt_1_is_a_pointered_cut(self):
        """tick_block > 1 under fused serving is the speculative-verify
        shape (ROADMAP item 6) — must refuse with a pointer, and the
        lint's REQUIRED_CUTS keeps the refusal from silently
        vanishing."""
        with pytest.raises(NotImplementedError, match="ROADMAP"):
            _stub_srv(tick_block=4)

    def test_six_tuple_bundle_refused(self):
        class OldStub(StubModel):
            def _decode_bundle(self, *a, **kw):
                return StubModel._decode_bundle(self, *a, **kw)[:6]

        with pytest.raises(ValueError, match="fused-tick entry"):
            ContinuousBatchingServer(OldStub(), max_cache_len=32,
                                     cache_backend="paged", page_size=4,
                                     serving_mode="fused")
        # without serving_mode="fused" the 6-tuple stays fully usable
        srv = ContinuousBatchingServer(OldStub(), max_cache_len=32,
                                       cache_backend="paged",
                                       page_size=4)
        rid = srv.submit(np.arange(5, dtype=np.int32), max_new_tokens=3)
        np.testing.assert_array_equal(
            srv.run()[rid], stub_tokens(np.arange(5, dtype=np.int32), 3))
