"""Launcher controller modes: ps env protocol, rpc endpoint, restart
(reference launch/controllers/{collective,ps,rpc}.py + controller.py:72)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PS_PROBE = """
import json, os, sys
role = os.environ.get("TRAINING_ROLE")
out = {
    "role": role,
    "id": os.environ.get("PADDLE_PSERVER_ID" if role == "PSERVER"
                         else "PADDLE_TRAINER_ID"),
    "servers": os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST"),
    "trainers_num": os.environ.get("PADDLE_TRAINERS_NUM"),
}
path = os.environ["PROBE_DIR"] + f"/{role}_{out['id']}.json"
json.dump(out, open(path, "w"))
"""

RESTART_PROBE = """
import os, sys
marker = os.environ["PROBE_DIR"] + "/attempt"
n = 0
if os.path.exists(marker):
    n = int(open(marker).read())
open(marker, "w").write(str(n + 1))
sys.exit(1 if n == 0 else 0)   # fail once, succeed on restart
"""


def _launch(tmp_path, script_body, extra_args, extra_env=None):
    script = tmp_path / "probe.py"
    script.write_text(script_body)
    env = dict(os.environ)
    env["PROBE_DIR"] = str(tmp_path)
    env.update(extra_env or {})
    cmd = [sys.executable, "-m", "paddle_tpu.parallel.launch.main",
           "--log_dir", str(tmp_path / "log"), *extra_args, str(script)]
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=240)


def test_ps_mode_env_protocol(tmp_path):
    r = _launch(tmp_path, PS_PROBE,
                ["--run_mode", "ps", "--server_num", "1",
                 "--trainer_num", "2"])
    assert r.returncode == 0, (r.stdout, r.stderr)
    srv = json.load(open(tmp_path / "PSERVER_0.json"))
    assert srv["servers"].startswith("127.0.0.1:")
    t0 = json.load(open(tmp_path / "TRAINER_0.json"))
    t1 = json.load(open(tmp_path / "TRAINER_1.json"))
    assert t0["trainers_num"] == "2" and t1["id"] == "1"
    assert t0["servers"] == srv["servers"]


def test_rpc_mode_sets_master_endpoint(tmp_path):
    body = """
import json, os
json.dump({"ep": os.environ.get("PADDLE_MASTER_ENDPOINT")},
          open(os.environ["PROBE_DIR"] + "/rpc.json", "w"))
"""
    r = _launch(tmp_path, body,
                ["--run_mode", "rpc", "--master", "127.0.0.1:29901"])
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert json.load(open(tmp_path / "rpc.json"))["ep"] == \
        "127.0.0.1:29901"


def test_watch_restarts_failed_worker(tmp_path):
    r = _launch(tmp_path, RESTART_PROBE, ["--max_restart", "1"])
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert open(tmp_path / "attempt").read() == "2"
