"""Quantization package tests: fake quant math oracle, QAT swap + STE
training, PTQ observer calibration."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q


def test_fake_quant_oracle():
    import jax.numpy as jnp
    x = np.array([-1.0, -0.5, 0.0, 0.3, 1.0], np.float32)
    scale = 1.0
    out = np.asarray(Q.fake_quant(jnp.asarray(x), scale, 8))
    ref = np.clip(np.round(x / scale * 127), -127, 127) * scale / 127
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_quant_dequant_straight_through_grad():
    import jax
    import jax.numpy as jnp
    g = jax.grad(lambda x: Q.quant_dequant(x, 1.0).sum())(
        jnp.asarray([0.3, -0.7]))
    np.testing.assert_allclose(np.asarray(g), [1.0, 1.0])


def test_qat_quantize_swaps_linear():
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMaxObserver(),
                        weight=Q.FakeQuanterChannelWiseAbsMax())
    qat = Q.QAT(cfg)
    qmodel = qat.quantize(model)
    kinds = [type(m).__name__ for _, m in qmodel.named_sublayers()]
    assert kinds.count("QuantedLinear") == 2


def test_qat_model_trains():
    pt.seed(0)
    rng = np.random.RandomState(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMaxObserver(),
                        weight=Q.FakeQuanterChannelWiseAbsMax())
    qmodel = Q.QAT(cfg).quantize(model)
    qmodel.train()
    opt = pt.optimizer.AdamW(learning_rate=5e-2,
                             parameters=qmodel.parameters())
    x = pt.to_tensor(rng.randn(32, 8).astype(np.float32))
    y = pt.to_tensor(rng.randint(0, 2, size=(32,)))
    losses = []
    for _ in range(25):
        loss = nn.functional.cross_entropy(qmodel(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_qat_output_is_quantized():
    """Quantized forward differs from fp forward but stays close."""
    pt.seed(0)
    rng = np.random.RandomState(0)
    lin = nn.Linear(8, 8)
    x = pt.to_tensor(rng.randn(4, 8).astype(np.float32))
    ref = lin(x).numpy()
    cfg = Q.QuantConfig(weight=Q.FakeQuanterChannelWiseAbsMax())
    q = Q.QuantedLinear(lin, cfg._default)
    out = q(x).numpy()
    assert not np.allclose(out, ref)
    assert np.abs(out - ref).max() < 0.1  # 8-bit error bound


def test_ptq_observer_calibration():
    rng = np.random.RandomState(0)
    obs_factory = Q.AbsmaxObserver()
    cfg = Q.QuantConfig(activation=obs_factory)
    model = nn.Sequential(nn.Linear(4, 4))
    pmodel = Q.PTQ(cfg).quantize(model)
    # calibration pass
    for _ in range(3):
        pmodel(pt.to_tensor(rng.randn(8, 4).astype(np.float32) * 3))
    (name, quanted), = [kv for kv in pmodel.named_sublayers()
                        if type(kv[1]).__name__ == "QuantedLinear"]
    scale = float(quanted.activation_quanter.scales().numpy())
    assert scale > 2.0  # saw abs values around 3*|randn|


def test_type_and_name_config():
    model = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    cfg = Q.QuantConfig()
    cfg.add_type_config(nn.Linear,
                        weight=Q.FakeQuanterChannelWiseAbsMax())
    qmodel = Q.QAT(cfg).quantize(model)
    kinds = [type(m).__name__ for _, m in qmodel.named_sublayers()]
    assert kinds.count("QuantedLinear") == 2


def test_convert_freezes_observer():
    rng = np.random.RandomState(0)
    cfg = Q.QuantConfig(activation=Q.AbsmaxObserver())
    model = Q.PTQ(cfg).quantize(nn.Sequential(nn.Linear(4, 4)))
    model(pt.to_tensor(rng.randn(8, 4).astype(np.float32)))
    ptq = Q.PTQ(cfg)
    frozen = ptq.convert(model)
    (_, quanted), = [kv for kv in frozen.named_sublayers()
                     if type(kv[1]).__name__ == "QuantedLinear"]
    before = float(quanted.activation_quanter.scales().numpy())
    frozen(pt.to_tensor(rng.randn(8, 4).astype(np.float32) * 100))
    after = float(quanted.activation_quanter.scales().numpy())
    assert before == after  # outlier serving batch must not move scales
    # the live calibration model still observes (inplace=False semantics)
    (_, live_q), = [kv for kv in model.named_sublayers()
                    if type(kv[1]).__name__ == "QuantedLinear"]
    assert live_q.activation_quanter._frozen is False


def test_double_quantize_does_not_double_wrap():
    model = nn.Sequential(nn.Conv2D(3, 4, 3), nn.Linear(4, 4))
    cfg = Q.QuantConfig(weight=Q.FakeQuanterChannelWiseAbsMax())
    qat = Q.QAT(cfg)
    qmodel = qat.quantize(model)
    qmodel2 = qat.quantize(qmodel)
    kinds = [type(m).__name__ for _, m in qmodel2.named_sublayers()]
    assert kinds.count("QuantedConv2D") == 1
    assert kinds.count("QuantedLinear") == 1
    assert kinds.count("Conv2D") == 0


def test_quanted_conv2d_matches_unquantized_closely():
    pt.seed(0)
    rng = np.random.RandomState(0)
    conv = nn.Conv2D(2, 3, 3, padding=1)
    x = pt.to_tensor(rng.randn(1, 2, 6, 6).astype(np.float32))
    ref = conv(x).numpy()
    cfg = Q.QuantConfig(weight=Q.FakeQuanterChannelWiseAbsMax(quant_axis=0))
    out = Q.QuantedConv2D(conv, cfg._default)(x).numpy()
    assert np.abs(out - ref).max() < 0.15


def test_quantize_not_inplace_preserves_original():
    model = nn.Sequential(nn.Linear(4, 4))
    cfg = Q.QuantConfig(weight=Q.FakeQuanterChannelWiseAbsMax())
    qmodel = Q.QAT(cfg).quantize(model, inplace=False)
    kinds = [type(m).__name__ for _, m in model.named_sublayers()]
    qkinds = [type(m).__name__ for _, m in qmodel.named_sublayers()]
    assert "QuantedLinear" not in kinds  # fp original untouched
    assert "QuantedLinear" in qkinds


def test_channelwise_axis_inferred_per_layer_kind():
    conv, lin = nn.Conv2D(2, 3, 3), nn.Linear(5, 7)
    cfg = Q.QuantConfig(weight=Q.FakeQuanterChannelWiseAbsMax())
    qc = Q.QuantedConv2D(conv, cfg._default)
    ql = Q.QuantedLinear(lin, cfg._default)
    assert qc.weight_quanter.quant_axis() == 0  # conv OIHW out-channel
    assert ql.weight_quanter.quant_axis() == 1  # linear [in, out] out-col
    x = pt.to_tensor(np.ones((1, 2, 5, 5), np.float32))
    qc(x)
    assert qc.weight_quanter.scales().shape == [3, 1, 1, 1]



def test_transpose_conv_quant_axis():
    convT = nn.Conv2DTranspose(4, 6, 3)
    cfg = Q.QuantConfig(weight=Q.FakeQuanterChannelWiseAbsMax())
    q = cfg._default.weight._instance(convT)
    assert q.quant_axis() == 1  # [in, out//g, kh, kw] out-channel axis


def test_nan_inf_flag_accepts_bool_and_strings():
    from paddle_tpu import runtime
    from paddle_tpu.core import tensor as ct
    runtime.set_flags({"FLAGS_check_nan_inf": True})
    assert ct._check_nan_inf is True
    runtime.set_flags({"FLAGS_check_nan_inf": "false"})
    assert ct._check_nan_inf is False
    runtime.set_flags({"FLAGS_check_nan_inf": "1"})
    assert ct._check_nan_inf is True
    runtime.set_flags({"FLAGS_check_nan_inf": 0})
    assert ct._check_nan_inf is False


def test_int8_inference_pallas_matmul():
    """True-int8 deploy path: Pallas int8 MXU matmul with fused dequant
    approximates the fp32 network closely."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.quantization import to_int8_inference
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(64, 128), pt.nn.GELU(),
                           pt.nn.Linear(128, 32))
    x = pt.to_tensor(np.random.randn(8, 64).astype("float32"))
    ref = net(x).numpy()
    q = to_int8_inference(net)
    out = q(x).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.06
    np.testing.assert_allclose(net(x).numpy(), ref)   # original untouched


def test_quantized_matmul_kernel_accuracy():
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas.quant_matmul import (quantize_tensor,
                                                    quantized_matmul)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 512)).astype("float32")
    w = rng.normal(size=(512, 256)).astype("float32")
    qx, sx = quantize_tensor(jnp.asarray(x))
    qw, sw = quantize_tensor(jnp.asarray(w), per_channel_axis=1)
    out = quantized_matmul(qx, qw, sx, sw, block_m=128, block_n=128,
                           block_k=128, interpret=True)
    ref = x @ w
    rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
    assert rel < 0.02
    # ragged fallback path
    out2 = quantized_matmul(qx[:100], qw, sx, sw, interpret=True)
    rel2 = np.abs(np.asarray(out2) - ref[:100]).max() / np.abs(ref).max()
    assert rel2 < 0.02
