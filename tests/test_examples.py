"""Examples are living documentation: each must run end-to-end.

Marked slow (compile-heavy); default suite runs one representative.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL = ["train_gpt2.py", "finetune_bert.py", "train_moe.py",
       "train_diffusion.py", "data_parallel.py", "tensor_parallel.py",
       "export_serve.py", "hapi_fit.py", "train_hybrid.py",
       "engine_pipeline.py", "generate_text.py"]


def _run(name):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run([sys.executable, os.path.join(REPO, "examples", name)],
                       capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, f"{name} failed:\n{r.stdout}\n{r.stderr}"


def test_example_data_parallel():
    _run("data_parallel.py")


@pytest.mark.slow
@pytest.mark.parametrize("name", [n for n in ALL
                                  if n != "data_parallel.py"])
def test_example(name):
    _run(name)
