"""Vision model zoo forward-shape tests (reference test_vision_models.py
pattern: construct + forward on a small input)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import models as M


def _run(model, size=64, channels=3, classes=10):
    x = pt.to_tensor(np.random.RandomState(0).randn(
        1, channels, size, size).astype(np.float32))
    model.eval()
    out = model(x)
    assert out.shape == [1, classes]


# one representative runs by default; the rest are `slow` (eager CNN
# forwards on this one-core box are compile-bound — each costs 30-60 s)
@pytest.mark.parametrize("fn", [
    lambda: M.alexnet(num_classes=10),
])
def test_small_nets_forward(fn):
    _run(fn(), size=64)


@pytest.mark.slow
@pytest.mark.parametrize("fn", [
    lambda: M.mobilenet_v2(num_classes=10),
    lambda: M.mobilenet_v1(num_classes=10),
    lambda: M.mobilenet_v3_small(num_classes=10),
    lambda: M.mobilenet_v3_large(num_classes=10),
    lambda: M.squeezenet1_0(num_classes=10),
    lambda: M.squeezenet1_1(num_classes=10),
    lambda: M.shufflenet_v2_x1_0(num_classes=10),
])
def test_small_nets_forward_full_zoo(fn):
    _run(fn(), size=64)


@pytest.mark.slow
@pytest.mark.parametrize("fn", [
    lambda: M.densenet121(num_classes=10),
    lambda: M.googlenet(num_classes=10),
    lambda: M.inception_v3(num_classes=10),
])
def test_big_nets_forward(fn):
    _run(fn(), size=96)


@pytest.mark.slow
def test_resnext_and_wide():
    _run(M.resnext50_32x4d(num_classes=10), size=64)
    _run(M.wide_resnet50_2(num_classes=10), size=64)


def test_vgg_variants_construct():
    # vgg13/19 construction alone costs ~20 s each here (the 25088x4096
    # classifier init); one variant by default, rest slow
    m = M.vgg11(num_classes=10)
    assert isinstance(m, M.VGG)


@pytest.mark.slow
def test_vgg_variants_construct_full():
    for f in (M.vgg13, M.vgg19):
        assert isinstance(f(num_classes=10), M.VGG)


@pytest.mark.slow
def test_mobilenet_v2_trains():
    pt.seed(0)
    import paddle_tpu.nn as nn
    m = M.mobilenet_v2(num_classes=4, scale=0.25)
    m.train()
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(4, 3, 32, 32).astype(np.float32))
    y = pt.to_tensor(rng.randint(0, 4, size=(4,)))
    l0 = None
    for i in range(6):
        loss = nn.functional.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if l0 is None:
            l0 = float(loss.numpy())
    assert float(loss.numpy()) < l0


@pytest.mark.slow
def test_adaptive_pool_non_divisible_matches_torch():
    # slow: the torch import alone costs seconds on this box; the
    # upsample-case shape contract below stays tier-1
    import torch
    import torch.nn.functional as TF
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 7, 5).astype(np.float32)
    got = pt.nn.functional.adaptive_avg_pool2d(
        pt.to_tensor(x), (3, 2)).numpy()
    ref = TF.adaptive_avg_pool2d(torch.from_numpy(x), (3, 2)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    got = pt.nn.functional.adaptive_max_pool2d(
        pt.to_tensor(x), (3, 2)).numpy()
    ref = TF.adaptive_max_pool2d(torch.from_numpy(x), (3, 2)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


@pytest.mark.slow
def test_adaptive_pool_upsample_case():
    # in_size < out_size (AlexNet on small inputs)
    x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    out = pt.nn.functional.adaptive_avg_pool2d(pt.to_tensor(x), (4, 4))
    assert out.shape == [1, 1, 4, 4]
