"""Goodput ledger, fleet metric merge, and SLO burn-rate alerts
(ISSUE 11).

Contracts under test:

- ``GoodputLedger``: per-tick attribution whose kinds sum EXACTLY to
  the tick's device tokens — asserted against hand-derived oracles for
  the dense backend, paged+dense prefill, and paged+ragged prefill,
  including a forced mid-prefill workload (``null_redirect`` from
  slots riding the decode program) and a deterministic preemption +
  replay workload (``replay`` matches the preempt-event oracle);
  registered-tail re-prefill and pow2 chunk pad are attributed; a
  DISABLED ledger is treated exactly like None (zero locks — it never
  reads a clock at all).
- fleet merge: ``merge_snapshots`` folds counters/gauges/histograms
  (labeled children included) and ``/fleet`` serves ONE Prometheus
  page whose parsed values equal the element-wise sum of the per-
  replica pages (render -> parse round trip).
- SLOs: burn rates fire ``page`` on sustained burn across BOTH
  windows, a short spike alone does not page, recovery clears — all on
  FakeClock, no sleeps; a disabled engine reads no clock and never
  calls its source; ``/slo`` + the ``/healthz`` ``"slo"`` detail.
- postmortem persistence: atomic JSON files, bounded newest-wins
  retention, restart-safe numbering.
- standalone journeys: a bare server constructed with ``journeys=``
  mints its own timelines; router-supplied handles still win.
- metric-docs lint: declared ``labelnames`` must appear in README's
  brace groups.

Everything runs on the StubModel double — tier-1 fast, no transformer
compiles."""
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from _serving_stub import StubModel, stub_tokens
from paddle_tpu.inference.continuous_batching import ContinuousBatchingServer
from paddle_tpu.inference.router import ReplicaRouter
from paddle_tpu.inference.serving import serve_metrics
from paddle_tpu.telemetry import (SLO, FakeClock, FlightRecorder,
                                  GoodputLedger, JourneyRecorder,
                                  MetricRegistry, SLOEngine,
                                  ServerTelemetry, merge_snapshots,
                                  parse_prometheus, render_snapshot)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _prompt(*toks):
    return np.asarray(toks, np.int32)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


class _CountingLock:
    def __init__(self):
        self.acquisitions = 0

    def __enter__(self):
        self.acquisitions += 1
        return self

    def __exit__(self, *exc):
        return False


# --------------------------------------------------------------------------
# GoodputLedger unit contracts
# --------------------------------------------------------------------------
class TestGoodputLedger:
    def test_add_flush_totals(self):
        led = GoodputLedger()
        led.add("goodput", 3)
        led.add("null_redirect", 1)
        led.add("goodput", 1)
        led.add("chunk_pad", 0)          # zero adds are dropped
        tick = led.flush_tick()
        assert tick == {"goodput": 4, "null_redirect": 1}
        assert led.flush_tick() is None          # empty tick: nothing
        led.add("replay", 2)
        led.flush_tick()
        assert led.totals() == {"goodput": 4, "null_redirect": 1,
                                "replay": 2}
        assert led.ticks == 2
        snap = led.snapshot()
        assert snap["total"] == 7
        assert snap["goodput_ratio"] == pytest.approx(4 / 7)
        assert snap["last_tick"] == {"replay": 2}
        assert snap["last_tick_ratio"] == 0.0

    def test_idle_ledger_ratio_is_one(self):
        led = GoodputLedger()
        assert led.goodput_ratio() == 1.0
        assert led.snapshot()["goodput_ratio"] == 1.0

    def test_metrics_published(self):
        reg = MetricRegistry()
        led = GoodputLedger(registry=reg)
        led.add("goodput", 3)
        led.add("replay", 1)
        led.flush_tick()
        tok = reg.get("server_tokens_total")
        assert tok.labels(kind="goodput").value == 3
        assert tok.labels(kind="replay").value == 1
        assert reg.get("serving_goodput_ratio").value == \
            pytest.approx(0.75)

    def test_disabled_ledger_zero_locks_and_server_treats_as_none(self):
        led = GoodputLedger(enabled=False)
        lock = _CountingLock()
        led._lock = lock
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16, ledger=led)
        assert srv._led is None
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        out = srv.run()
        np.testing.assert_array_equal(out[rid], stub_tokens([1, 2, 3], 3))
        assert lock.acquisitions == 0 and led._tick == {}
        assert srv.goodput() is None


# --------------------------------------------------------------------------
# Conservation: kinds sum to total device tokens, per mode
# --------------------------------------------------------------------------
class TestLedgerConservation:
    """Each scenario's FULL totals dict is asserted against a
    hand-derived oracle; conservation (kinds sum to rows + masked page
    DMAs) is checked explicitly against the independently counted
    decode dispatches and prefill launches."""

    def _conserve(self, led, srv, n_decode, prefill_rows, dma):
        """sum(kinds) == decode rows + prefill rows + masked DMAs."""
        totals = led.totals()
        rows = n_decode * srv.max_slots * srv.tick_block
        assert sum(totals.values()) == rows + prefill_rows + dma

    def test_dense_backend(self):
        # prompt 3, budget 3, 2 slots: prefill 3 rows; 2 decode
        # dispatches x 2 rows (1 active + 1 empty each)
        led = GoodputLedger()
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16, ledger=led)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        out = srv.run()
        np.testing.assert_array_equal(out[rid], stub_tokens([1, 2, 3], 3))
        assert led.totals() == {"goodput": 5, "null_redirect": 2}
        self._conserve(led, srv, n_decode=2, prefill_rows=3, dma=0)

    def test_dense_backend_chunk_pad_and_block_waste(self):
        # prompt 5 chunk 2 -> 1 pad row; tick_block 2 budget 2:
        # decode block emits token #2 then wastes 1 row; the empty
        # slot rides 2 rows
        led = GoodputLedger()
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       prefill_chunk=2, tick_block=2,
                                       ledger=led)
        rid = srv.submit(_prompt(1, 2, 3, 4, 5), max_new_tokens=2)
        out = srv.run()
        np.testing.assert_array_equal(out[rid],
                                      stub_tokens([1, 2, 3, 4, 5], 2))
        assert led.totals() == {"goodput": 6, "chunk_pad": 1,
                                "block_waste": 1, "null_redirect": 2}
        self._conserve(led, srv, n_decode=1, prefill_rows=6, dma=0)

    def test_paged_dense_prefill(self):
        # paged backend, dense prefill detour: same rows as dense plus
        # the decode kernel's masked page DMAs — table width 4 pages,
        # live ceil((3+1)/4)=1 then ceil(5/4)=2 -> (4-1)*4 + (4-2)*4
        led = GoodputLedger()
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       cache_backend="paged",
                                       page_size=4,
                                       prefill_mode="dense",
                                       ledger=led)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        out = srv.run()
        np.testing.assert_array_equal(out[rid], stub_tokens([1, 2, 3], 3))
        assert led.totals() == {"goodput": 5, "null_redirect": 2,
                                "skipped_page_dma": 20}
        self._conserve(led, srv, n_decode=2, prefill_rows=3, dma=20)

    def test_paged_ragged_prefill(self):
        # ragged launch pads the 3-token chunk to C=4 (pow2 ladder) and
        # DMAs the full 4-page table: prefill dma (4-1)*4, decode dma
        # (4-1)*4 then (4-2)*4
        led = GoodputLedger()
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       cache_backend="paged",
                                       page_size=4, ledger=led)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        out = srv.run()
        np.testing.assert_array_equal(out[rid], stub_tokens([1, 2, 3], 3))
        assert led.totals() == {"goodput": 5, "chunk_pad": 1,
                                "null_redirect": 2,
                                "skipped_page_dma": 32}
        self._conserve(led, srv, n_decode=2, prefill_rows=4, dma=32)

    def test_ragged_mid_prefill_null_redirect(self):
        """Forced mid-prefill: prompt 6 streams in at 3 tokens/tick
        while the short request decodes — the mid-prefill slot rides
        the decode program as null-redirected rows, the oracle counts
        them from the tick schedule."""
        led = GoodputLedger()
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       cache_backend="paged",
                                       page_size=4,
                                       prefill_tokens_per_tick=3,
                                       ledger=led)
        ra = srv.submit(_prompt(1, 2, 3), max_new_tokens=2)
        rb = srv.submit(_prompt(4, 5, 6, 7, 8, 9), max_new_tokens=2)
        out = srv.run()
        np.testing.assert_array_equal(out[ra], stub_tokens([1, 2, 3], 2))
        np.testing.assert_array_equal(
            out[rb], stub_tokens([4, 5, 6, 7, 8, 9], 2))
        # tick1: A prefills 3 rows (C=4, pad 1; dma 12), activates,
        #   decodes (B mid-prefill -> null 1; A live 1pg -> dma 12,
        #   goodput 1 -> finished at budget 2? no: emitted 2 -> done)
        # tick2: B chunk rows 0..2 (C=4, pad 1, dma 12); A got
        #   harvested at tick1's post-decode harvest, B mid-prefill
        #   -> no active slots -> NO decode dispatch
        # tick3: B chunk rows 3..5 (C=4, pad 1, live 2pg -> dma 8),
        #   activates; decode: empty slot null 1; B live 2pg -> dma 8,
        #   goodput 1 -> emitted 2 -> finished
        assert led.totals() == {"goodput": 11, "chunk_pad": 3,
                                "null_redirect": 2,
                                "skipped_page_dma": 52}
        self._conserve(led, srv, n_decode=2, prefill_rows=12, dma=52)

    def test_registered_tail_reprefill(self):
        """Ragged matching is page-granular: the registered prefix's
        sub-page tail re-prefills with the remainder and is attributed
        tail_reprefill, not goodput."""
        led = GoodputLedger()
        srv = ContinuousBatchingServer(StubModel(), max_slots=1,
                                       max_cache_len=32,
                                       cache_backend="paged",
                                       page_size=4, ledger=led)
        pre = _prompt(1, 2, 3, 4, 5, 6)          # 1 full page + tail 2
        srv.register_prefix(pre)
        assert led.totals() == {}    # operator setup stays OFF ledger
        ids = np.concatenate([pre, _prompt(7, 8, 9, 10)])
        rid = srv.submit(ids, max_new_tokens=2)
        out = srv.run()
        np.testing.assert_array_equal(out[rid], stub_tokens(ids, 2))
        # prefill: rows 4..9 (tree hit covers page 1 = 4 tokens):
        # positions 4,5 redo the registered tail -> tail_reprefill 2,
        # 6..9 -> goodput 4; C=8 -> pad 2; maxp 8, live 3 -> dma 20.
        # decode (1 tick): live ceil(11/4)=3 -> dma 20, goodput 1.
        assert led.totals() == {"goodput": 5, "tail_reprefill": 2,
                                "chunk_pad": 2,
                                "skipped_page_dma": 40}
        self._conserve(led, srv, n_decode=1, prefill_rows=8, dma=40)

    def test_preemption_replay_oracle(self):
        """The acceptance workload: optimistic admission over an
        undersized pool forces one deterministic self-preemption; the
        victim's replay (prompt re-prefill + re-decoded rows below its
        parked offset) must match the oracle derived from the preempt
        event, and null_redirect must match the tick-occupancy oracle
        from the flight recorder."""
        led = GoodputLedger()
        rec = FlightRecorder()
        tele = ServerTelemetry()
        srv = ContinuousBatchingServer(
            StubModel(), max_slots=2, max_cache_len=16,
            cache_backend="paged", page_size=4, num_pages=6,
            admission="optimistic", headroom_pages=1,
            ledger=led, recorder=rec, telemetry=tele)
        ra = srv.submit(_prompt(1, 2, 3, 4), max_new_tokens=8)
        rb = srv.submit(_prompt(5, 6, 7, 8), max_new_tokens=8)
        out = srv.run()
        # pressure degrades throughput, never correctness
        np.testing.assert_array_equal(out[ra],
                                      stub_tokens([1, 2, 3, 4], 8))
        np.testing.assert_array_equal(out[rb],
                                      stub_tokens([5, 6, 7, 8], 8))
        assert srv.stats["preemptions"] == 1
        assert srv.stats["preempt_resumed"] == 1
        totals = led.totals()
        # replay oracle from the recorder's preempt event: the victim
        # parked holding `tokens` emitted; its cold-donated prompt page
        # was reclaimed by the very grow that displaced it, so the
        # replay re-prefills the whole prompt (4 rows) and re-decodes
        # tokens 2..tokens (the first token re-emits from the prefill
        # logits row, not a decode row)
        (pev,) = rec.events(kind="preempt")
        assert totals["replay"] == 4 + (pev["tokens"] - 1) == 8
        # null-redirect oracle from the INDEPENDENT telemetry counter
        # (PR-2 instrumentation at the dispatch site): the ledger's
        # attribution must agree with it row for row
        assert totals["null_redirect"] == tele.registry.get(
            "kv_null_redirected_writes_total").value == 6
        # the full hand-derived ledger (see the trace in this test's
        # design): conservation over 12 decode dispatches, 2 prefill
        # launches (2x4 + 1x4 rows at C=4), and the masked page DMAs
        assert totals == {"goodput": 22, "replay": 8,
                          "null_redirect": 6, "skipped_page_dma": 156}
        ticks = [e for e in rec.events(kind="tick")
                 if "decode" in e["dispatches"]]
        self._conserve(led, srv, n_decode=len(ticks),
                       prefill_rows=12, dma=156)

    def test_stats_and_postmortem_carry_goodput(self):
        led = GoodputLedger()
        rec = FlightRecorder()
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       cache_backend="paged",
                                       page_size=4, telemetry=True,
                                       ledger=led, recorder=rec)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        srv.run()
        assert srv.goodput()["tokens"] == led.totals()
        ms = serve_metrics(srv)
        try:
            _, body = _get(ms.url + "/stats")
            stats = json.loads(body)["stats"]
            assert stats["goodput"]["tokens"]["goodput"] == 5
            assert 0 < stats["goodput"]["goodput_ratio"] < 1
        finally:
            ms.close()
        srv.kill()
        bundle = srv.postmortems()[-1]
        assert bundle["goodput"]["tokens"] == led.totals()


# --------------------------------------------------------------------------
# Fleet metric merge + /fleet
# --------------------------------------------------------------------------
class TestFleetMerge:
    def _registry(self):
        r = MetricRegistry()
        r.counter("c_total", "c").inc(0)
        r.gauge("g", "g")
        r.histogram("h_seconds", "h", buckets=(0.1, 1.0))
        r.counter("lab_total", "l", labelnames=("k",))
        return r

    def test_merge_counters_gauges_histograms_and_labels(self):
        r1, r2 = self._registry(), self._registry()
        r1.get("c_total").inc(2)
        r2.get("c_total").inc(5)
        r1.get("g").set(3)
        r2.get("g").set(4)
        r1.get("h_seconds").observe(0.05)
        r1.get("h_seconds").observe(0.5)
        r2.get("h_seconds").observe(0.05)
        r1.get("lab_total").labels(k="a").inc(1)
        r2.get("lab_total").labels(k="a").inc(2)
        r2.get("lab_total").labels(k="b").inc(7)   # r2-only child
        snap = merge_snapshots([r1.snapshot(), r2.snapshot()])
        assert snap["c_total"]["samples"][()] == 7
        assert snap["g"]["samples"][()] == 7
        h = snap["h_seconds"]["samples"][()]
        assert h["count"] == 3 and h["sum"] == pytest.approx(0.6)
        assert h["buckets"] == [(0.1, 2), (1.0, 3), ("+Inf", 3)]
        lab = snap["lab_total"]["samples"]
        assert lab[("a",)] == 3 and lab[("b",)] == 7
        # inputs not mutated
        assert r1.snapshot()["c_total"]["samples"][()] == 2

    def test_ratio_gauges_merge_by_mean_not_sum(self):
        """Summing two replicas' 0.7 goodput ratios into 1.4 would be
        an impossible fleet reading — *_ratio gauges fold by mean over
        the replicas that report them."""
        r1, r2, r3 = (MetricRegistry() for _ in range(3))
        for r, v in ((r1, 0.8), (r2, 0.4)):
            r.gauge("serving_goodput_ratio", "g").set(v)
        r3.gauge("other", "g").set(1.0)      # no ratio gauge at all
        snap = merge_snapshots([r1.snapshot(), r2.snapshot(),
                                r3.snapshot()])
        assert snap["serving_goodput_ratio"]["samples"][()] == \
            pytest.approx(0.6)
        assert snap["other"]["samples"][()] == 1.0

    def test_merge_rejects_kind_and_bucket_mismatch(self):
        r1, r2 = MetricRegistry(), MetricRegistry()
        r1.counter("x", "x")
        r2.gauge("x", "x")
        with pytest.raises(ValueError, match="disagrees"):
            merge_snapshots([r1.snapshot(), r2.snapshot()])
        r3, r4 = MetricRegistry(), MetricRegistry()
        r3.histogram("h", "h", buckets=(1.0,))
        r4.histogram("h", "h", buckets=(2.0,))
        with pytest.raises(ValueError, match="bucket"):
            merge_snapshots([r3.snapshot(), r4.snapshot()])

    def test_round_trip_equals_elementwise_sum(self):
        """render(merge) -> parse equals the per-replica parses summed
        key-by-key — histograms bucket-wise, labeled children
        included."""
        r1, r2 = self._registry(), self._registry()
        r1.get("c_total").inc(1)
        r2.get("c_total").inc(2)
        r1.get("h_seconds").observe(0.05)
        r2.get("h_seconds").observe(2.0)
        r1.get("lab_total").labels(k="x").inc(4)
        r2.get("lab_total").labels(k="x").inc(5)
        merged = parse_prometheus(render_snapshot(
            merge_snapshots([r1.snapshot(), r2.snapshot()])))
        p1 = parse_prometheus(r1.render())
        p2 = parse_prometheus(r2.render())
        want = dict(p1)
        for key, v in p2.items():
            want[key] = want.get(key, 0.0) + v
        assert merged == want

    def _fleet(self, n=2):
        reps = [ContinuousBatchingServer(
            StubModel(), max_slots=2, max_cache_len=32,
            cache_backend="paged", page_size=8,
            telemetry=ServerTelemetry()) for _ in range(n)]
        return ReplicaRouter(reps, telemetry=True), reps

    def test_router_fleet_endpoint_round_trip(self):
        router, reps = self._fleet()
        for rep in reps:
            rep.start()
        for i in range(4):
            router.wait(router.submit(_prompt(1 + i, 2, 3),
                                      max_new_tokens=4))
        # drain + stop BEFORE snapshotting: a serve thread finishing
        # its tick after wait() returns must not race the comparison
        router.stop()
        pages = [parse_prometheus(
            rep.telemetry.registry.render()) for rep in reps]
        pages.append(parse_prometheus(
            router.telemetry.registry.render()))
        want = {}
        for page in pages:
            for key, v in page.items():
                want[key] = want.get(key, 0.0) + v
        ms = serve_metrics(router)
        try:
            _, body = _get(ms.url + "/fleet")
            assert parse_prometheus(body) == want
            # a fleet's worth of requests on one page
            assert body.count("serving_requests_total") >= 1
        finally:
            ms.close()


# --------------------------------------------------------------------------
# SLO engine
# --------------------------------------------------------------------------
class TestSLO:
    def _setup(self, **kw):
        reg = MetricRegistry()
        h = reg.histogram("serving_ttft_seconds", "ttft",
                          buckets=(0.1, 1.0))
        req = reg.counter("serving_requests_total", "req",
                          labelnames=("state",))
        fc = FakeClock()
        kw.setdefault("threshold", 0.1)
        kw.setdefault("fast_window", 10)
        slo = SLO("ttft", "ttft", target=0.9, window=120, **kw)
        eng = SLOEngine([slo], lambda: reg.snapshot(), clock=fc)
        return reg, h, req, fc, eng

    def test_declaration_validation(self):
        with pytest.raises(ValueError, match="objective"):
            SLO("x", "p99", 0.9, 60)
        with pytest.raises(ValueError, match="threshold"):
            SLO("x", "ttft", 0.9, 60)
        with pytest.raises(ValueError, match="target"):
            SLO("x", "availability", 1.0, 60)
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine([SLO("a", "availability", 0.9, 60),
                       SLO("a", "availability", 0.99, 60)],
                      lambda: {})

    def test_fire_on_sustained_burn_and_clear_on_recovery(self):
        reg, h, req, fc, eng = self._setup()
        for _ in range(10):
            h.observe(0.05)
        assert eng.evaluate()[0]["state"] == "ok"
        # sustained burn: every request blows the threshold for 5s —
        # both windows see bad_frac 1.0 -> burn 10 >= page_burn
        fc.advance(5)
        for _ in range(20):
            h.observe(0.5)
        rep = eng.evaluate()[0]
        assert rep["state"] == "page"
        assert rep["burn"]["long"] == pytest.approx(10.0)
        assert rep["burn"]["short"] == pytest.approx(10.0)
        # recovery: the short window goes clean, min(burns) drops
        fc.advance(20)
        for _ in range(200):
            h.observe(0.05)
        rep = eng.evaluate()[0]
        assert rep["state"] == "ok"
        assert rep["burn"]["short"] == pytest.approx(0.0)
        assert [(t["from"], t["to"]) for t in eng.transitions] == \
            [("ok", "page"), ("page", "ok")]
        # the transition log is bounded like every buffer here — a
        # flapping SLO probed for weeks must not grow without limit
        assert eng.transitions.maxlen is not None

    def test_short_spike_alone_does_not_page(self):
        """The multi-window rule: a burst of bad requests pages only
        if the LONG window is burning too."""
        reg, h, req, fc, eng = self._setup()
        # 20 minutes of clean traffic fills the long window
        for i in range(12):
            for _ in range(100):
                h.observe(0.05)
            fc.advance(10)
            assert eng.evaluate()[0]["state"] == "ok"
        # a spike with nothing else in the short window (one full
        # fast_window past the last clean sample): it burns hard
        # there, the long window barely moves
        fc.advance(10)
        for _ in range(30):
            h.observe(0.5)
        rep = eng.evaluate()[0]
        assert rep["burn"]["short"] >= 10.0
        assert rep["burn"]["long"] < 2.0
        assert rep["state"] == "ok"

    def test_availability_objective(self):
        reg = MetricRegistry()
        req = reg.counter("serving_requests_total", "req",
                          labelnames=("state",))
        fc = FakeClock()
        eng = SLOEngine(
            [SLO("avail", "availability", target=0.99, window=60,
                 fast_window=5, page_burn=10.0)],
            lambda: reg.snapshot(), clock=fc)
        req.labels(state="finished").inc(100)
        eng.evaluate()
        fc.advance(3)
        req.labels(state="failed").inc(50)
        req.labels(state="finished").inc(50)
        rep = eng.evaluate()[0]
        assert rep["state"] == "page"        # 50% failures vs 1% budget
        assert rep["good"] == 150 and rep["total"] == 200

    def test_disabled_engine_zero_clock_zero_source_calls(self):
        fc = FakeClock()

        def poisoned_source():
            raise AssertionError("disabled engine must not sample")

        eng = SLOEngine([SLO("a", "availability", 0.9, 60)],
                        poisoned_source, clock=fc, enabled=False)
        assert eng.evaluate() == []
        assert fc.reads == 0
        # the router treats it exactly like None
        rep = ContinuousBatchingServer(StubModel(), max_slots=1,
                                       max_cache_len=16)
        router = ReplicaRouter([rep], slos=eng)
        assert router._slo is None and router.slo_report() is None

    def test_slo_evaluation_error_never_kills_healthz(self):
        """A mixed-version fleet whose registries disagree makes
        evaluation raise: /slo must answer 500 with the error (not a
        dropped connection) and /healthz must keep its 200 verdict
        with the detail served from cached states."""
        rep = ContinuousBatchingServer(StubModel(), max_slots=1,
                                       max_cache_len=16,
                                       telemetry=True)

        def poisoned_source():
            raise ValueError("metric 'x' disagrees across replicas")

        eng = SLOEngine([SLO("avail", "availability", 0.9, 60)],
                        poisoned_source)
        router = ReplicaRouter([rep], telemetry=True, slos=eng)
        ms = serve_metrics(router)
        try:
            status, body = _get(ms.url + "/healthz")
            health = json.loads(body)
            assert status == 200
            assert health["slo"] == {"worst": "ok", "alerts": {}}
            try:
                _get(ms.url + "/slo")
                raise AssertionError("expected HTTP 500")
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert "disagrees" in e.read().decode()
        finally:
            ms.close()

    def test_router_slo_and_healthz_detail_endpoints(self):
        fc = FakeClock()
        tele = ServerTelemetry(clock=fc)
        rep = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=32,
                                       cache_backend="paged",
                                       page_size=8, telemetry=tele,
                                       clock=fc)
        router = ReplicaRouter(
            [rep], telemetry=True, clock=fc,
            slos=[SLO("avail", "availability", target=0.9, window=60,
                      fast_window=5)])
        rep.start()
        try:
            router.wait(router.submit(_prompt(1, 2, 3),
                                      max_new_tokens=4))
            ms = serve_metrics(router)
            try:
                status, body = _get(ms.url + "/slo")
                payload = json.loads(body)["slos"]
                assert payload[0]["name"] == "avail"
                assert payload[0]["state"] == "ok"
                status, body = _get(ms.url + "/healthz")
                health = json.loads(body)
                assert status == 200 and health["state"] == "healthy"
                assert health["slo"] == {"worst": "ok", "alerts": {}}
                # burn metrics landed on the router registry
                assert router.telemetry.registry.get(
                    "slo_state").labels(slo="avail").value == 0
            finally:
                ms.close()
        finally:
            rep.stop()


class TestSLOBackgroundEvaluator:
    """ISSUE 12 satellite (PR 10 known cut): ``start(interval)`` keeps
    the cached alert states — the ``/healthz`` SLO detail — fresh on a
    background thread, without anything scraping ``/slo``."""

    def test_states_refresh_without_explicit_evaluate(self):
        reg = MetricRegistry()
        req = reg.counter("serving_requests_total", "req",
                          labelnames=("state",))
        fc = FakeClock()
        slo = SLO("avail", "availability", target=0.9, window=120,
                  fast_window=10)
        eng = SLOEngine([slo], lambda: reg.snapshot(), clock=fc)
        assert eng.start(interval=0.01) is eng
        try:
            deadline = time.monotonic() + 5
            while not eng._samples["avail"]:
                assert time.monotonic() < deadline, "never evaluated"
                time.sleep(0.005)
            assert eng.states() == {"avail": "ok"}
            # budget starts burning hard; the DETAIL flips to page with
            # nobody calling evaluate() or scraping /slo
            req.labels(state="failed").inc(50)
            fc.advance(5.0)
            deadline = time.monotonic() + 5
            while eng.state("avail") != "page":
                assert time.monotonic() < deadline, \
                    f"state stuck at {eng.states()}"
                time.sleep(0.005)
        finally:
            eng.close()
        # close() JOINED the thread: samples stop accumulating
        n = len(eng._samples["avail"])
        time.sleep(0.05)
        assert len(eng._samples["avail"]) == n
        # still usable pull-driven afterwards
        fc.advance(1.0)
        assert eng.evaluate()[0]["name"] == "avail"

    def test_evaluation_errors_counted_thread_survives(self):
        calls = []

        def flaky_source():
            calls.append(0)
            if len(calls) == 1:
                raise ValueError("transient scrape failure")
            return {}

        eng = SLOEngine([SLO("a", "availability", 0.9, 60)],
                        flaky_source, clock=FakeClock())
        eng.start(interval=0.01)
        try:
            deadline = time.monotonic() + 5
            while len(calls) < 3:
                assert time.monotonic() < deadline, "thread died"
                time.sleep(0.005)
        finally:
            eng.close()
        assert eng.eval_errors == 1
        assert isinstance(eng.last_eval_error, ValueError)

    def test_disabled_engine_start_is_a_noop(self):
        eng = SLOEngine([SLO("a", "availability", 0.9, 60)],
                        lambda: {}, enabled=False)
        assert eng.start(interval=0.01) is eng
        assert eng._thread is None
        eng.close()                          # idempotent, no thread

    def test_double_start_refused_and_interval_validated(self):
        eng = SLOEngine([SLO("a", "availability", 0.9, 60)],
                        lambda: {}, clock=FakeClock())
        with pytest.raises(ValueError, match="interval"):
            eng.start(interval=0)
        eng.start(interval=60)
        try:
            with pytest.raises(RuntimeError, match="already started"):
                eng.start(interval=60)
        finally:
            eng.close()


# --------------------------------------------------------------------------
# Postmortem persistence
# --------------------------------------------------------------------------
class TestPostmortemDir:
    def test_atomic_files_bounded_newest_wins(self, tmp_path):
        d = str(tmp_path / "pm")
        rec = FlightRecorder(clock=FakeClock(), max_postmortems=2,
                             postmortem_dir=d)
        rec.record("ev", i=1)
        for i in range(3):
            rec.postmortem(f"reason{i}", extra=i)
        files = sorted(os.listdir(d))
        assert files == ["postmortem-00000001.json",
                         "postmortem-00000002.json"]
        with open(os.path.join(d, files[-1])) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "reason2" and bundle["extra"] == 2
        assert bundle["events"][0]["kind"] == "ev"
        assert not [fn for fn in files if fn.endswith(".tmp")]
        assert rec.persist_errors == 0
        # in-memory store unchanged by persistence
        assert [b["reason"] for b in rec.postmortems()] == \
            ["reason1", "reason2"]

    def test_zero_retention_keeps_zero_files(self, tmp_path):
        """max_postmortems=0 must not leak disk files: the in-memory
        deque retains nothing and persistence is skipped outright
        (regression: the prune slice [:-0] was a silent no-op)."""
        d = str(tmp_path / "pm")
        rec = FlightRecorder(clock=FakeClock(), max_postmortems=0,
                             postmortem_dir=d)
        rec.postmortem("incident")
        rec.postmortem("another")
        assert os.listdir(d) == [] and rec.postmortems() == []

    def test_numbering_survives_restart(self, tmp_path):
        d = str(tmp_path / "pm")
        rec1 = FlightRecorder(clock=FakeClock(), max_postmortems=4,
                              postmortem_dir=d)
        rec1.postmortem("first")
        rec2 = FlightRecorder(clock=FakeClock(), max_postmortems=4,
                              postmortem_dir=d)
        rec2.postmortem("after-restart")
        files = sorted(os.listdir(d))
        assert files == ["postmortem-00000000.json",
                         "postmortem-00000001.json"]
        with open(os.path.join(d, files[1])) as f:
            assert json.load(f)["reason"] == "after-restart"

    def test_server_kill_persists_crash_scene(self, tmp_path):
        d = str(tmp_path / "pm")
        rec = FlightRecorder(postmortem_dir=d)
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       cache_backend="paged",
                                       page_size=4, recorder=rec)
        srv.submit(_prompt(1, 2, 3), max_new_tokens=4)
        srv.kill()
        (fn,) = os.listdir(d)
        with open(os.path.join(d, fn)) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "killed"
        assert bundle["queue"] == [0]       # the crash scene, frozen


# --------------------------------------------------------------------------
# Standalone server journeys
# --------------------------------------------------------------------------
class TestStandaloneJourneys:
    def test_bare_server_mints_and_serves_journeys(self):
        srv = ContinuousBatchingServer(StubModel(), max_slots=2,
                                       max_cache_len=16,
                                       cache_backend="paged",
                                       page_size=4, telemetry=True,
                                       journeys=True)
        rid = srv.submit(_prompt(1, 2, 3), max_new_tokens=3)
        srv.run()
        tl = srv.journey(rid)
        phases = [e["phase"] for e in tl]
        assert phases[:2] == ["submitted", "queued"]
        assert "first_token" in phases and phases[-1] == "finished"
        assert all(e["where"] == "server" for e in tl)
        assert srv.journey(999) is None
        ms = serve_metrics(srv)
        try:
            status, body = _get(ms.url + f"/debug/journey/{rid}")
            assert status == 200
            assert json.loads(body)["journey"][0]["phase"] == \
                "submitted"
        finally:
            ms.close()

    def test_router_supplied_journey_wins(self):
        jr = JourneyRecorder()
        srv = ContinuousBatchingServer(StubModel(), max_slots=1,
                                       max_cache_len=16, journeys=jr)
        handle = jr.begin("r7", where="router").at("replica0")
        rid = srv.submit(_prompt(1, 2), max_new_tokens=2,
                         journey=handle)
        srv.run()
        # no server-minted timeline; the router-supplied one got the
        # lifecycle events at its own location label
        assert srv.journey(rid) is None
        assert [e["where"] for e in jr.journey("r7")] == \
            ["replica0"] * len(jr.journey("r7"))

    def test_disabled_journeys_treated_as_none(self):
        fc = FakeClock()
        jr = JourneyRecorder(clock=fc, enabled=False)
        srv = ContinuousBatchingServer(StubModel(), max_slots=1,
                                       max_cache_len=16, journeys=jr)
        assert srv._jrec is None
        rid = srv.submit(_prompt(1, 2), max_new_tokens=2)
        srv.run()
        assert fc.reads == 0 and srv.journey(rid) is None


# --------------------------------------------------------------------------
# Metric-docs lint: label coverage
# --------------------------------------------------------------------------
class TestMetricDocsLabels:
    def _mod(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_metric_docs",
            os.path.join(REPO, "scripts", "check_metric_docs.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_scan_finds_declared_labels(self):
        mod = self._mod()
        labels = mod.registered_labels(os.path.join(REPO, "paddle_tpu"))
        assert labels["server_tokens_total"] == ["kind"]
        assert labels["slo_burn_rate"] == ["slo", "window"]
        assert labels["server_dispatches_total"] == ["op"]
        # unlabeled metrics never appear
        assert "serving_tick_dispatches" not in labels

    def test_detects_missing_and_accepts_brace_styles(self):
        mod = self._mod()
        readme = ("documented: a_total{kind} and "
                  "b_total{op=x|y} and c_total bare and "
                  "d_total{slo,\n  window=long|short}")
        bad = mod.undocumented_labels(
            {"a_total": ["kind"], "b_total": ["op"],
             "c_total": ["state"], "d_total": ["slo", "window"],
             "e_total": ["point"]}, readme)
        assert bad == [("c_total", ["state"]), ("e_total", ["point"])]

    def test_repo_labels_are_clean(self, capsys):
        mod = self._mod()
        assert mod.main(["check_metric_docs.py"]) == 0
        assert "labeled" in capsys.readouterr().out
