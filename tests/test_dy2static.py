"""dy2static AST transform tests (reference: dygraph_to_static test suite
pattern — same function must agree eagerly and traced)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.jit.dy2static import (Dy2StaticError, convert_to_static)


def _agree(fn, *np_args, jit_also=True):
    """Transformed fn must match the original eagerly AND under jax.jit."""
    static = convert_to_static(fn)
    ref = fn(*[np.asarray(a) for a in np_args])
    got_eager = static(*[np.asarray(a) for a in np_args])
    np.testing.assert_allclose(np.asarray(got_eager), np.asarray(ref),
                               rtol=1e-6)
    if jit_also:
        got_jit = jax.jit(static)(*[jnp.asarray(a) for a in np_args])
        np.testing.assert_allclose(np.asarray(got_jit), np.asarray(ref),
                                   rtol=1e-6)


class TestIfElse:
    def test_simple_if(self):
        def f(x):
            y = x * 2
            if x.sum() > 0:
                y = y + 1
            else:
                y = y - 1
            return y

        _agree(f, np.array([1.0, 2.0], np.float32))
        _agree(f, np.array([-1.0, -2.0], np.float32))

    def test_if_without_else(self):
        def f(x):
            y = x
            if x.sum() > 0:
                y = y * 10
            return y

        _agree(f, np.array([3.0], np.float32))
        _agree(f, np.array([-3.0], np.float32))

    def test_nested_if(self):
        def f(x):
            y = x
            if x.sum() > 0:
                if x.sum() > 10:
                    y = y * 100
                else:
                    y = y * 10
            else:
                y = -y
            return y

        for v in ([20.0], [5.0], [-5.0]):
            _agree(f, np.array(v, np.float32))

    def test_python_bool_stays_python(self):
        def f(x, flag):
            y = x
            if flag:
                y = y + 1
            return y

        static = convert_to_static(f)
        out = static(np.array([1.0], np.float32), True)
        np.testing.assert_allclose(np.asarray(out), [2.0])
        out = static(np.array([1.0], np.float32), False)
        np.testing.assert_allclose(np.asarray(out), [1.0])


class TestLoops:
    def test_while_loop(self):
        def f(x):
            i = jnp.asarray(0)
            s = x * 0
            while i < 5:
                s = s + x
                i = i + 1
            return s

        _agree(f, np.array([2.0], np.float32))

    def test_while_data_dependent_bound(self):
        def f(x, n):
            s = x * 0
            i = n * 0
            while i < n:
                s = s + x
                i = i + 1
            return s

        static = convert_to_static(f)
        got = jax.jit(static)(jnp.asarray([3.0]), jnp.asarray(4))
        np.testing.assert_allclose(np.asarray(got), [12.0])

    def test_for_range(self):
        def f(x):
            acc = x * 0
            for i in range(4):
                acc = acc + x * i
            return acc

        _agree(f, np.array([1.0, 2.0], np.float32))

    def test_for_range_traced_bound(self):
        def f(x, n):
            acc = x * 0
            for i in range(n):
                acc = acc + x
            return acc

        static = convert_to_static(f)
        got = jax.jit(static)(jnp.asarray([5.0]), jnp.asarray(3))
        np.testing.assert_allclose(np.asarray(got), [15.0])


class TestLogical:
    def test_and_or_not(self):
        def f(x):
            a = x.sum() > 0
            b = x.sum() < 10
            y = x
            if a and b:
                y = y + 100
            if a or b:
                y = y + 1
            if not a:
                y = y - 1000
            return y

        for v in ([5.0], [20.0], [-5.0]):
            _agree(f, np.array(v, np.float32))


class TestToStaticIntegration:
    def test_to_static_with_control_flow(self):
        @pt.jit.to_static
        def relu_like(x):
            y = x
            if x.sum() > 0:
                y = y * 2
            else:
                y = y * 0
            return y

        out = relu_like(pt.to_tensor(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])
        out = relu_like(pt.to_tensor(np.array([-1.0, -2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [0.0, 0.0])

    def test_layer_with_loop_under_jit(self):
        def body(x, steps):
            acc = x * 0
            for i in range(steps):
                acc = acc + jnp.sin(x + i)
            return acc

        static = convert_to_static(body)
        ref = body(np.asarray([0.5], np.float32), 3)
        got = jax.jit(static, static_argnums=())(
            jnp.asarray([0.5]), jnp.asarray(3))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5)

    def test_mismatched_branches_raise_clearly(self):
        def f(x):
            if x.sum() > 0:
                y = jnp.ones((2,))
            else:
                y = jnp.ones((3,))
            return y

        static = convert_to_static(f)
        with pytest.raises(Exception):
            jax.jit(static)(jnp.asarray([1.0]))

    def test_scalar_pred_requirement(self):
        def f(x):
            y = x
            if x > 0:  # vector predicate
                y = y + 1
            return y

        static = convert_to_static(f)
        with pytest.raises(Dy2StaticError, match="scalar"):
            jax.jit(static)(jnp.asarray([1.0, -1.0]))


class TestNewTransformers:
    def test_ifexp_traced(self):
        def f(x):
            return (x * 2 if x.sum() > 0 else x * 3) + 1

        _agree(f, np.array([1.0, 2.0], np.float32))
        _agree(f, np.array([-1.0, -2.0], np.float32))

    def test_assert_eager_raises(self):
        def f(x):
            assert x.sum() > 0, "negative!"
            return x

        static = convert_to_static(f)
        out = static(np.array([1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [1.0])
        with pytest.raises(AssertionError, match="negative"):
            static(np.array([-1.0], np.float32))

    def test_assert_traced_is_noop(self):
        def f(x):
            assert x.sum() > -1e9
            return x * 2

        static = convert_to_static(f)
        out = jax.jit(static)(jnp.array([2.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [4.0])

    def test_print_traced_compiles(self, capfd):
        def f(x):
            print(x)
            return x + 1

        static = convert_to_static(f)
        out = jax.jit(static)(jnp.array([1.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0])

    def test_print_eager_passthrough(self, capsys):
        def f(x):
            print("value:", x)
            return x

        static = convert_to_static(f)
        static(np.array([5.0], np.float32))
        assert "value:" in capsys.readouterr().out

    def test_ifexp_tuple_branches_traced(self):
        def f(x):
            a, b = (x * 2, x + 1) if x.sum() > 0 else (x * 3, x - 1)
            return a + b

        static = convert_to_static(f)
        out = jax.jit(static)(jnp.array([1.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [4.0])

    def test_print_label_and_tensor_traced(self):
        def f(x):
            print("loss:", x)
            return x * 2

        static = convert_to_static(f)
        out = jax.jit(static)(jnp.array([3.0], jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [6.0])


def test_print_shadowing_not_rewritten():
    """A local binding of `print` must win over the convert_print rewrite."""
    import paddle_tpu as pt
    collected = []

    @pt.jit.to_static
    def fn(x):
        print = collected.append   # noqa: A001 - deliberate shadow
        print(7)
        return x * 2

    out = fn(pt.to_tensor([3.0]))
    np.testing.assert_allclose(out.numpy(), [6.0])
    assert collected == [7]


def test_assert_message_lazy():
    """assert messages evaluate only on failure (Python semantics)."""
    import paddle_tpu as pt
    errors = []

    @pt.jit.to_static
    def fn(x):
        assert True, f"err: {errors[0]}"   # IndexError if evaluated eagerly
        return x + 1

    np.testing.assert_allclose(fn(pt.to_tensor([1.0])).numpy(), [2.0])


def test_print_with_keywords_converted(capsys):
    """print(..., flush=True) still routes through convert_print."""
    import paddle_tpu as pt

    @pt.jit.to_static
    def fn(x):
        print("val:", 3, flush=True)
        return x * 2

    out = fn(pt.to_tensor([2.0]))
    np.testing.assert_allclose(out.numpy(), [4.0])
    assert "val: 3" in capsys.readouterr().out
