"""Combined TP×PP×ZeRO(×DP) hybrid step (VERDICT r3 #2).

Reference: fleet.distributed_model composes mp/pp/sharding/dp groups in one
model (python/paddle/distributed/fleet/fleet.py:385-428); here ONE jitted
program (shard_map 1F1B with mp psums + GSPMD ZeRO update) does all four.
Parity oracle: the same model on full weights, sequentially, one device.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu._compat import host_memory_kind

_HOST_KIND = host_memory_kind()

# every test here compiles multi-device shard_map+scan programs (the
# repo's costliest CPU-mesh compiles, ~200s of tier-1 wall on this
# container); the whole module rides the slow lane — `pytest -m slow`
pytestmark = pytest.mark.slow
from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                        init_llama_tp_params,
                                        make_llama_tp_fns)
from paddle_tpu.parallel.mesh import P
from paddle_tpu.parallel.pp_1f1b import segment_counts

NH, L, H, F, V = 4, 4, 16, 32, 64
B, S, M = 4, 8, 2


def _ref_block(p, x):
    def rms(x, w, eps=1e-5):
        var = jnp.mean(jnp.square(x), -1, keepdims=True)
        return x * jax.lax.rsqrt(var + eps) * w

    mb, s, h = x.shape
    hn = rms(x, p["ln1"])
    q = (hn @ p["wq"]).reshape(mb, s, NH, -1)
    k = (hn @ p["wk"]).reshape(mb, s, NH, -1)
    v = (hn @ p["wv"]).reshape(mb, s, NH, -1)
    dh = q.shape[-1]
    lg = jnp.einsum("bqnd,bknd->bnqk", q, k) / np.sqrt(dh)
    mask = jnp.tril(jnp.ones((s, s), bool))
    lg = jnp.where(mask, lg, jnp.finfo(lg.dtype).min)
    attn = jax.nn.softmax(lg, -1)
    ctx = jnp.einsum("bnqk,bknd->bqnd", attn, v).reshape(mb, s, -1)
    x = x + ctx @ p["wo"]
    hn = rms(x, p["ln2"])
    x = x + (jax.nn.silu(hn @ p["wg"]) * (hn @ p["wu"])) @ p["wd"]
    return x


def _ref_loss(blocks, embed, head, ids, labels):
    x = embed["table"][ids]
    for bp in blocks:
        x = _ref_block(bp, x)
    lg = (x @ head["wo"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(lg, -1)
    return -jnp.take_along_axis(logp, labels[..., None], -1).mean()


def _setup(zero_stage=1, dp=1, pp=2, sharding=2, mp=2):
    mesh = dist.init_mesh(dp=dp, pp=pp, sharding=sharding, mp=mp)
    fns, specs = make_llama_tp_fns(NH, mp)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(7))
    opt = pt.optimizer.AdamW(learning_rate=1e-3)
    step_fn, params, opt_state, shards = build_hybrid_train_step(
        *fns, blocks, embed, head, mesh, opt, num_micro=M,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=zero_stage)
    return mesh, (blocks, embed, head), step_fn, params, opt_state, shards


def test_hybrid_loss_matches_sequential_reference():
    _mesh, (blocks, embed, head), step_fn, params, opt_state, _sh = _setup()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    loss, params, opt_state = step_fn(params, opt_state, ids, labels, 1)
    ref = _ref_loss(blocks, embed, head, ids, labels)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)


def test_hybrid_grads_match_sequential_reference():
    mesh, (blocks, embed, head), _f, _p, _s, _sh = _setup()
    fns, specs = make_llama_tp_fns(NH, 2)
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    grad_fn, (stacked, emb_p, head_p, _sched) = build_1f1b_train_step(
        *fns, blocks, embed, head, mesh, num_micro=M,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], batch_axes=("dp", "sharding"))
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    loss, (d_blk, d_emb, d_head) = jax.jit(grad_fn)(
        stacked, emb_p, head_p, ids, labels)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda t: _ref_loss(t["blocks"], t["embed"], t["head"], ids,
                            labels))({"blocks": blocks, "embed": embed,
                                      "head": head})
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(d_emb["table"]),
                               np.asarray(ref_grads["embed"]["table"]),
                               rtol=5e-3, atol=2e-5)
    np.testing.assert_allclose(np.asarray(d_head["wo"]),
                               np.asarray(ref_grads["head"]["wo"]),
                               rtol=5e-3, atol=2e-5)
    # unstack [v, S, C, ...] -> per-layer and compare every block grad
    Sdeg = mesh.degree("pp")
    counts, starts = segment_counts(L, Sdeg)   # VS = S (v=1)
    for vs in range(Sdeg):
        for j in range(int(counts[vs])):
            layer = int(starts[vs]) + j
            for name in ("wq", "wo", "wd", "ln1"):
                got = np.asarray(d_blk[name][0, vs, j])
                want = np.asarray(ref_grads["blocks"][layer][name])
                np.testing.assert_allclose(
                    got, want, rtol=5e-3, atol=2e-5,
                    err_msg=f"layer {layer} {name}")


def test_hybrid_zero_shards_opt_state():
    _m, _t, _f, params, opt_state, (p_sh, s_sh) = _setup(zero_stage=1)
    # moments sharded over the ZeRO axis; params not
    assert "sharding" in str(s_sh["m"]["blocks"]["wq"].spec)
    assert "sharding" not in str(p_sh["blocks"]["wq"].spec)
    # mp/pp axes shard both
    assert "mp" in str(p_sh["blocks"]["wq"].spec)
    assert "pp" in str(p_sh["blocks"]["wq"].spec)


def test_hybrid_zero3_shards_params():
    _m, _t, step_fn, params, opt_state, (p_sh, _s) = _setup(zero_stage=3)
    assert "sharding" in str(p_sh["blocks"]["wq"].spec)
    rng = np.random.RandomState(2)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    loss, params, opt_state = step_fn(params, opt_state, ids, ids, 1)
    assert np.isfinite(float(loss))


def test_hybrid_train_loss_decreases():
    _m, _t, step_fn, params, opt_state, _sh = _setup()
    rng = np.random.RandomState(3)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    losses = []
    for i in range(1, 6):
        loss, params, opt_state = step_fn(params, opt_state, ids, ids, i)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------- tied embed/head


def test_tied_embedding_pp_matches_sequential():
    """tie_embed_head: head = embed^T, table pp-sharded (VERDICT r3 #3).
    Reference: SharedLayerDesc (pp_layers.py:430-517)."""
    from paddle_tpu.parallel.pp_1f1b import (build_1f1b_train_step,
                                             make_tied_lm_fns)
    mesh = dist.init_mesh(dp=2, pp=4)
    rng = np.random.RandomState(11)
    Lt, Ht, Vt = 8, 16, 64
    blocks = [{"w": jnp.asarray(rng.randn(Ht, Ht).astype(np.float32) * .3)}
              for _ in range(Lt)]
    table = rng.randn(Vt, Ht).astype(np.float32) * 0.3
    embed = {"table": jnp.asarray(table)}

    def block_fn(p, x):
        return jnp.tanh(x @ p["w"])

    embed_fn, head_loss_fn = make_tied_lm_fns()
    grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
        block_fn, embed_fn, head_loss_fn, blocks, embed, {}, mesh,
        num_micro=4, tie_embed_head=True)
    ids = jnp.asarray(rng.randint(0, Vt, size=(8, 8)).astype(np.int32))
    loss, (d_blk, d_emb, d_head) = jax.jit(grad_fn)(
        stacked, emb_p, head_p, ids, ids)

    # sequential reference with explicitly tied weights
    def ref(tb):
        x = tb[ids]
        for bp in blocks:
            x = jnp.tanh(x @ bp["w"])
        lg = (x @ tb.T).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, ids[..., None], -1).mean()

    ref_loss, ref_dtab = jax.value_and_grad(ref)(jnp.asarray(table))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(d_emb["table"]),
                               np.asarray(ref_dtab), rtol=5e-3, atol=2e-5)
    assert d_head == {}, "tied mode must emit no separate head grads"


def test_tied_embedding_pp_memory_accounting():
    """Params and grads of the shared table live pp-SHARDED: each stage
    holds [V/S, h], not a full replica (the reference keeps full fp32
    grad accumulators for the shared weight on every stage)."""
    from paddle_tpu.parallel.pp_1f1b import (build_1f1b_train_step,
                                             make_tied_lm_fns)
    mesh = dist.init_mesh(dp=2, pp=4)
    rng = np.random.RandomState(12)
    Lt, Ht, Vt = 4, 16, 64
    blocks = [{"w": jnp.asarray(rng.randn(Ht, Ht).astype(np.float32) * .3)}
              for _ in range(Lt)]
    embed = {"table": jnp.asarray(rng.randn(Vt, Ht).astype(np.float32))}
    embed_fn, head_loss_fn = make_tied_lm_fns()
    grad_fn, (stacked, emb_p, _hp, _s) = build_1f1b_train_step(
        lambda p, x: jnp.tanh(x @ p["w"]), embed_fn, head_loss_fn,
        blocks, embed, {}, mesh, num_micro=2, tie_embed_head=True)
    # stored table is sharded over pp: local shard = V/S rows
    assert "pp" in str(emb_p["table"].sharding.spec)
    shard_shapes = {tuple(s.data.shape)
                    for s in emb_p["table"].addressable_shards}
    assert shard_shapes == {(Vt // 4, Ht)}, shard_shapes
    ids = jnp.asarray(rng.randint(0, Vt, size=(4, 8)).astype(np.int32))
    _loss, (_db, d_emb, d_head) = jax.jit(grad_fn)(
        stacked, emb_p, {}, ids, ids)
    assert d_head == {}
    g_shards = {tuple(s.data.shape)
                for s in d_emb["table"].addressable_shards}
    assert g_shards == {(Vt // 4, Ht)}, g_shards


def test_tied_tp_hybrid_matches_sequential():
    """tie_embed_head composed WITH TP inside the full hybrid step
    (mp2 x pp2 x sharding2): the 70B configuration with a shared
    vocab-parallel embedding. Oracle: sequential tied reference."""
    from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                            make_tied_tp_lm_fns)
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    rng = np.random.RandomState(21)
    blocks, embed, _head = init_llama_tp_params(L, H, F, V, rng=rng)
    fns, block_specs = make_tied_tp_lm_fns(NH, 2)
    opt = pt.optimizer.AdamW(learning_rate=1e-3)
    step_fn, params, opt_state, (p_sh, s_sh) = build_hybrid_train_step(
        *fns, blocks, embed, {}, mesh, opt, num_micro=M,
        block_param_specs=block_specs, zero_stage=1, tie_embed_head=True)
    # storage: table sharded over mp AND pp; no head tree
    assert "mp" in str(p_sh["embed"]["table"].spec)
    assert "pp" in str(p_sh["embed"]["table"].spec)
    assert params["head"] == {}
    shard_shapes = {tuple(s.data.shape)
                    for s in params["embed"]["table"].addressable_shards}
    assert shard_shapes == {(V // 4, H)}, shard_shapes

    rng2 = np.random.RandomState(22)
    ids = jnp.asarray(rng2.randint(0, V, size=(B, S)).astype(np.int32))
    labels = jnp.asarray(rng2.randint(0, V, size=(B, S)).astype(np.int32))
    loss, params, opt_state = step_fn(params, opt_state, ids, labels, 1)

    def ref(tb):
        x = tb[ids]
        for bp in blocks:
            x = _ref_block(bp, x)
        lg = (x @ tb.T).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, labels[..., None], -1).mean()

    ref_loss = ref(embed["table"])
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)


def test_tied_tp_hybrid_grads_match_sequential():
    from paddle_tpu.parallel.hybrid import make_tied_tp_lm_fns
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    rng = np.random.RandomState(23)
    blocks, embed, _head = init_llama_tp_params(L, H, F, V, rng=rng)
    fns, block_specs = make_tied_tp_lm_fns(NH, 2)
    grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
        *fns, blocks, embed, {}, mesh, num_micro=M,
        block_param_specs=block_specs, batch_axes=("dp", "sharding"),
        tie_embed_head=True)
    rng2 = np.random.RandomState(24)
    ids = jnp.asarray(rng2.randint(0, V, size=(B, S)).astype(np.int32))
    loss, (d_blk, d_emb, d_head) = jax.jit(grad_fn)(
        stacked, emb_p, head_p, ids, ids)
    assert d_head == {}

    def ref(tb):
        x = tb[ids]
        for bp in blocks:
            x = _ref_block(bp, x)
        lg = (x @ tb.T).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, ids[..., None], -1).mean()

    ref_loss, ref_dtab = jax.value_and_grad(ref)(embed["table"])
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(d_emb["table"]),
                               np.asarray(ref_dtab), rtol=5e-3,
                               atol=2e-5)


def test_hybrid_interleaved_virtual_stages_match():
    """interleave=2 (virtual pipeline stages, reference interleaved-1F1B
    pipeline_parallel.py:461) composed with TP: parity vs sequential."""
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(31))
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
        *fns, blocks, embed, head, mesh, num_micro=4, interleave=2,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], batch_axes=("dp", "sharding"))
    rng = np.random.RandomState(32)
    ids = jnp.asarray(rng.randint(0, V, size=(8, S)).astype(np.int32))
    loss, _grads = jax.jit(grad_fn)(stacked, emb_p, head_p, ids, ids)
    ref = _ref_loss(blocks, embed, head, ids, ids)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)


def test_hybrid_remat_dots_policy_matches():
    """remat_block='dots' (save MXU outputs, recompute elementwise) must
    not change numbers, only the memory/recompute tradeoff."""
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(41))
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    rng = np.random.RandomState(42)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    outs = {}
    for mode in (True, "dots", False):
        grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
            *fns, blocks, embed, head, mesh, num_micro=M,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], batch_axes=("dp", "sharding"),
            remat_block=mode)
        loss, (d_blk, _de, _dh) = jax.jit(grad_fn)(
            stacked, emb_p, head_p, ids, ids)
        outs[str(mode)] = (float(loss), np.asarray(d_blk["wq"]))
    l0, g0 = outs["True"]
    for k in ("dots", "False"):
        l, g = outs[k]
        np.testing.assert_allclose(l, l0, rtol=1e-5)
        np.testing.assert_allclose(g, g0, rtol=1e-4, atol=1e-6)


def test_tied_non_mp_fns_on_mp_mesh_raise():
    """code-review r4: make_tied_lm_fns assumes the FULL gathered table;
    on mp>1 meshes the builder must refuse it (the gather yields only
    [V/mp, h] and lookups would silently clamp)."""
    import pytest
    from paddle_tpu.parallel.pp_1f1b import (build_1f1b_train_step,
                                             make_tied_lm_fns)
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    blocks, embed, _h = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(51))
    embed_fn, head_loss_fn = make_tied_lm_fns()
    with pytest.raises(ValueError, match="make_tied_tp_lm_fns"):
        build_1f1b_train_step(
            lambda p, x: x, embed_fn, head_loss_fn, blocks, embed, {},
            mesh, num_micro=2, tie_embed_head=True)


def test_hybrid_gqa_rope_flash_paths_agree():
    """Production block options: GQA (2 kv heads for 4 q heads), RoPE,
    and the flash attention route must agree with the einsum route
    (flash falls back to the reference composition on CPU — independent
    code, same math)."""
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(61), n_heads=NH,
        n_kv_heads=2)
    rng = np.random.RandomState(62)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    outs = {}
    for flash in (False, True):
        fns, specs = make_llama_tp_fns(NH, 2, n_kv_heads=2,
                                       use_flash=flash,
                                       rope_theta=10000.0)
        grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
            *fns, blocks, embed, head, mesh, num_micro=M,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], batch_axes=("dp", "sharding"))
        loss, (d_blk, _de, _dh) = jax.jit(grad_fn)(
            stacked, emb_p, head_p, ids, ids)
        outs[flash] = (float(loss), np.asarray(d_blk["wk"]))
    l0, g0 = outs[False]
    l1, g1 = outs[True]
    np.testing.assert_allclose(l1, l0, rtol=1e-5)
    np.testing.assert_allclose(g1, g0, rtol=1e-3, atol=1e-6)
    # GQA actually shrank the kv projections
    assert g0.shape[-1] == H // NH * 2


def test_hybrid_sequence_parallel_ring_matches():
    """Context parallelism composed into the hybrid: sequence sharded
    over sp, ring attention inside the pipeline blocks, RoPE offset by
    sp rank (SURVEY north star: long context x tp x pp x zero). Parity
    vs the same model without sp."""
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(71))
    rng = np.random.RandomState(72)
    S_long = 16
    ids = jnp.asarray(rng.randint(0, V, size=(4, S_long)).astype(np.int32))

    # reference: mp-only mesh, flash path, same global sequence
    mesh0 = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns0, specs0 = make_llama_tp_fns(NH, 2, rope_theta=10000.0,
                                     use_flash=True)
    g0, (st0, e0, h0, _) = build_1f1b_train_step(
        *fns0, blocks, embed, head, mesh0, num_micro=2,
        block_param_specs=specs0[0], embed_param_specs=specs0[1],
        head_param_specs=specs0[2], batch_axes=("dp", "sharding"))
    loss0, (db0, _de0, _dh0) = jax.jit(g0)(st0, e0, h0, ids, ids)

    # sp: sequence sharded over 2 ranks, ring attention
    mesh1 = dist.init_mesh(dp=1, pp=2, sharding=1, sp=2, mp=2)
    fns1, specs1 = make_llama_tp_fns(NH, 2, rope_theta=10000.0,
                                     sp_axis="sp", sp_degree=2)
    g1, (st1, e1, h1, _) = build_1f1b_train_step(
        *fns1, blocks, embed, head, mesh1, num_micro=2,
        block_param_specs=specs1[0], embed_param_specs=specs1[1],
        head_param_specs=specs1[2], batch_axes=("dp", "sharding"),
        seq_axis="sp")
    loss1, (db1, _de1, _dh1) = jax.jit(g1)(st1, e1, h1, ids, ids)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(db1["wq"]),
                               np.asarray(db0["wq"]), rtol=5e-3,
                               atol=2e-5)


def test_uniform_collectives_tick_matches_cond_tick():
    """The uniform tick (compute-all + select) must equal the role-cond
    tick exactly on a non-sp config — same schedule, same numbers."""
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(81))
    rng = np.random.RandomState(82)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    outs = {}
    for uni in (False, True):
        grad_fn, (st, ep, hp, _s) = build_1f1b_train_step(
            *fns, blocks, embed, head, mesh, num_micro=M,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], batch_axes=("dp", "sharding"),
            uniform_collectives=uni)
        loss, (d_blk, d_emb, d_head) = jax.jit(grad_fn)(st, ep, hp,
                                                        ids, ids)
        outs[uni] = (float(loss), np.asarray(d_blk["wq"]),
                     np.asarray(d_emb["table"]),
                     np.asarray(d_head["wo"]))
    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-6)
    for i in (1, 2, 3):
        np.testing.assert_allclose(outs[True][i], outs[False][i],
                                   rtol=1e-4, atol=1e-7)


def test_moe_hybrid_matches_dense_reference():
    """Expert-parallel MoE block inside the hybrid pipeline (EP over mp,
    GShard dense dispatch): loss AND grads match a single-device dense
    reference with the full expert bank."""
    from paddle_tpu.parallel.hybrid import (init_moe_tp_params,
                                            make_moe_tp_fns)
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    E, K = 4, 2
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_moe_tp_fns(NH, 2, num_experts=E, top_k=K)
    blocks, embed, head = init_moe_tp_params(
        L, H, F, V, E, rng=np.random.RandomState(91))
    grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
        *fns, blocks, embed, head, mesh, num_micro=M,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], batch_axes=("dp", "sharding"))
    rng = np.random.RandomState(92)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    loss, (d_blk, d_emb, d_head) = jax.jit(grad_fn)(
        stacked, emb_p, head_p, ids, ids)

    def ref_moe_block(p, x):
        def rms(x, w, eps=1e-5):
            var = jnp.mean(jnp.square(x), -1, keepdims=True)
            return x * jax.lax.rsqrt(var + eps) * w
        # attention (same math as _ref_block's first half)
        mb, s, h = x.shape
        hn = rms(x, p["ln1"])
        q = (hn @ p["wq"]).reshape(mb, s, NH, -1)
        k = (hn @ p["wk"]).reshape(mb, s, NH, -1)
        v = (hn @ p["wv"]).reshape(mb, s, NH, -1)
        dh = q.shape[-1]
        lg = jnp.einsum("bqnd,bknd->bnqk", q, k) / np.sqrt(dh)
        mask = jnp.tril(jnp.ones((s, s), bool))
        lg = jnp.where(mask, lg, jnp.finfo(lg.dtype).min)
        attn = jax.nn.softmax(lg, -1)
        ctx = jnp.einsum("bnqk,bknd->bqnd", attn, v).reshape(mb, s, -1)
        x = x + ctx @ p["wo"]
        # dense MoE over ALL experts
        hn = rms(x, p["ln2"])
        logits = hn @ p["w_gate"]
        topv, topi = jax.lax.top_k(logits, K)
        probs = jax.nn.softmax(topv, -1)
        oh = jax.nn.one_hot(topi, E)
        comb = (oh * probs[..., None]).sum(-2)
        up = jnp.einsum("bsh,ehf->ebsf", hn, p["we_g"])
        up = jax.nn.silu(up) * jnp.einsum("bsh,ehf->ebsf", hn, p["we_u"])
        down = jnp.einsum("ebsf,efh->ebsh", up, p["we_d"])
        return x + jnp.einsum("ebsh,bse->bsh", down, comb)

    def ref(tree):
        x = tree["embed"]["table"][ids]
        for bp in tree["blocks"]:
            x = ref_moe_block(bp, x)
        lg = (x @ tree["head"]["wo"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, ids[..., None], -1).mean()

    tree = {"blocks": blocks, "embed": embed, "head": head}
    ref_loss, ref_grads = jax.value_and_grad(ref)(tree)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)
    np.testing.assert_allclose(
        np.asarray(d_blk["w_gate"][0, 0, 0]),
        np.asarray(ref_grads["blocks"][0]["w_gate"]), rtol=5e-3,
        atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(d_blk["we_d"][0, 0, 0]),
        np.asarray(ref_grads["blocks"][0]["we_d"]), rtol=5e-3, atol=2e-5)


def test_seq_axis_mismatch_raises():
    """code-review r4: sequence-sharded inputs into non-ring attention
    would silently train a wrong model — the builder refuses."""
    import pytest
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    mesh = dist.init_mesh(dp=1, pp=2, sharding=1, sp=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)      # built WITHOUT sp_axis
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(95))
    with pytest.raises(ValueError, match="sp_axis"):
        build_1f1b_train_step(
            *fns, blocks, embed, head, mesh, num_micro=2,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], seq_axis="sp")


def test_ring_attention_gqa_matches_repeated():
    """Ring permutes RAW GQA kv shards (ICI at kv size); result equals
    pre-repeated MHA ring."""
    from paddle_tpu.ops.pallas.ring_attention import ring_attention
    mesh = dist.init_mesh(dp=1, sp=4)
    rng = np.random.RandomState(96)
    Bq, Hq, Sq, D = 1, 4, 32, 8
    q = jnp.asarray(rng.randn(Bq, Hq, Sq, D).astype(np.float32))
    kv = jnp.asarray(rng.randn(Bq, 2, Sq, D).astype(np.float32))

    def body_gqa(q_, k_, v_):
        return ring_attention(q_, k_, v_, axis_name="sp", causal=True)

    def body_mha(q_, k_, v_):
        return ring_attention(q_, jnp.repeat(k_, 2, 1),
                              jnp.repeat(v_, 2, 1), axis_name="sp",
                              causal=True)

    specs_q = P(None, None, "sp")
    out_g = jax.shard_map(body_gqa, mesh=mesh.mesh,
                          in_specs=(specs_q,) * 3, out_specs=specs_q,
                          check_vma=False)(q, kv, kv)
    out_m = jax.shard_map(body_mha, mesh=mesh.mesh,
                          in_specs=(specs_q,) * 3, out_specs=specs_q,
                          check_vma=False)(q, kv, kv)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_m),
                               rtol=1e-5, atol=1e-6)


def test_hybrid_offload_keeps_state_on_host():
    """ZeRO host offload in the hybrid step: optimizer state lives in
    pinned_host between steps; numbers match the non-offload step."""
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(101))
    rng = np.random.RandomState(102)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    losses = {}
    for off in (False, True):
        opt = pt.optimizer.AdamW(learning_rate=1e-3)
        step_fn, params, opt_state, (p_sh, s_sh) = \
            build_hybrid_train_step(
                *fns, blocks, embed, head, mesh, opt, num_micro=M,
                block_param_specs=specs[0], embed_param_specs=specs[1],
                head_param_specs=specs[2], zero_stage=1, offload=off)
        if off:
            kinds = {s_sh["m"]["blocks"]["wq"].memory_kind}
            assert kinds == {_HOST_KIND}, kinds
            assert opt_state["m"]["blocks"]["wq"].sharding.memory_kind \
                == _HOST_KIND
        l1, params, opt_state = step_fn(params, opt_state, ids, ids, 1)
        l2, params, opt_state = step_fn(params, opt_state, ids, ids, 2)
        if off:
            assert opt_state["m"]["blocks"]["wq"].sharding.memory_kind \
                == _HOST_KIND
        losses[off] = (float(l1), float(l2))
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)


def test_hybrid_checkpoint_restacks_onto_different_pp():
    """Mesh-change restore for the hybrid step (reference
    auto_parallel/converter semantics): train on pp2, unstack to the
    canonical per-layer layout, restack onto pp4 — losses and grads
    carry over exactly. Optimizer moments restack with the same
    helpers (same tree layout as params)."""
    from paddle_tpu.parallel.hybrid import restack_blocks, unstack_blocks
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    Lc = 8
    blocks, embed, head = init_llama_tp_params(
        Lc, H, F, V, rng=np.random.RandomState(111))
    rng = np.random.RandomState(112)
    ids = jnp.asarray(rng.randint(0, V, size=(8, S)).astype(np.int32))

    fns, specs = make_llama_tp_fns(NH, 2)
    kw = dict(block_param_specs=specs[0], embed_param_specs=specs[1],
              head_param_specs=specs[2], batch_axes=("dp", "sharding"))

    mesh2 = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    g2, (st2, e2, h2, _) = build_1f1b_train_step(
        *fns, blocks, embed, head, mesh2, num_micro=4, **kw)
    loss2, (db2, _d, _h) = jax.jit(g2)(st2, e2, h2, ids, ids)

    # checkpoint: canonical layout from the pp2 stacks
    canon = unstack_blocks(st2, Lc, pp_degree=2)
    for layer in range(Lc):        # canonical layout == original params
        for nme in ("wq", "ln1"):
            np.testing.assert_array_equal(canon[layer][nme],
                                          np.asarray(blocks[layer][nme]))

    # restore onto pp4 x mp2
    mesh4 = dist.init_mesh(dp=1, pp=4, sharding=1, mp=2)
    g4, (st4, e4, h4, _) = build_1f1b_train_step(
        *fns, canon, embed, head, mesh4, num_micro=4,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], batch_axes=("dp", "sharding"))
    # restack_blocks produces the same stacks the builder makes
    restacked = restack_blocks(canon, mesh4)
    for nme in st4:
        np.testing.assert_array_equal(np.asarray(restacked[nme]),
                                      np.asarray(st4[nme]))
    loss4, (db4, _d4, _h4) = jax.jit(g4)(st4, e4, h4, ids, ids)
    np.testing.assert_allclose(float(loss4), float(loss2), rtol=1e-5)
    # grads agree layer-by-layer across the two pipeline layouts
    d2 = unstack_blocks(db2, Lc, pp_degree=2)
    d4 = unstack_blocks(db4, Lc, pp_degree=4)
    for layer in (0, 3, 7):
        np.testing.assert_allclose(d4[layer]["wq"], d2[layer]["wq"],
                                   rtol=1e-4, atol=1e-7,
                                   err_msg=f"layer {layer}")


def test_hybrid_grad_clip_matches_sequential():
    """Global-norm clipping inside the hybrid step spans every shard
    (pp-stacked blocks, mp slices): clipped update == sequential SGD-on-
    clipped-grads reference in norm terms."""
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(121))
    rng = np.random.RandomState(122)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    clip = 0.01
    opt = pt.optimizer.AdamW(learning_rate=1e-3)
    step_fn, params, opt_state, _sh = build_hybrid_train_step(
        *fns, blocks, embed, head, mesh, opt, num_micro=M,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=1, grad_clip_norm=clip)
    loss, params, opt_state = step_fn(params, opt_state, ids, ids, 1)
    assert np.isfinite(float(loss))

    # reference: same grads from the sequential model, same clip rule
    ref_loss, ref_grads = jax.value_and_grad(
        lambda t: _ref_loss(t["blocks"], t["embed"], t["head"], ids,
                            ids))({"blocks": blocks, "embed": embed,
                                   "head": head})
    gnorm = float(jnp.sqrt(sum(
        jnp.sum(jnp.square(g))
        for g in jax.tree_util.tree_leaves(ref_grads))))
    assert gnorm > clip, "pick a clip below the actual norm"
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-4)


def test_hybrid_ulysses_sp_matches_ring():
    """sp_mode='ulysses' (all_to_all heads<->sequence) inside the hybrid
    pipeline equals the ring mode numerically — with GQA (1 kv head per
    mp rank) to pin the kv-repeat guard."""
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(131), n_heads=NH,
        n_kv_heads=2)
    rng = np.random.RandomState(132)
    ids = jnp.asarray(rng.randint(0, V, size=(4, 16)).astype(np.int32))
    outs = {}
    for mode in ("ring", "ulysses"):
        mesh = dist.init_mesh(dp=1, pp=2, sharding=1, sp=2, mp=2)
        fns, specs = make_llama_tp_fns(NH, 2, rope_theta=10000.0,
                                       n_kv_heads=2, sp_axis="sp",
                                       sp_degree=2, sp_mode=mode)
        g, (st, ep, hp, _) = build_1f1b_train_step(
            *fns, blocks, embed, head, mesh, num_micro=2,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], batch_axes=("dp", "sharding"),
            seq_axis="sp")
        loss, (d_blk, _de, _dh) = jax.jit(g)(st, ep, hp, ids, ids)
        outs[mode] = (float(loss), np.asarray(d_blk["wq"]))
    np.testing.assert_allclose(outs["ulysses"][0], outs["ring"][0],
                               rtol=1e-5)
    np.testing.assert_allclose(outs["ulysses"][1], outs["ring"][1],
                               rtol=1e-3, atol=1e-6)


def test_multi_precision_master_weights():
    """AdamW(multi_precision=True): fp32 master accumulates updates that
    bf16 storage rounds away (reference multi_precision adam); the
    hybrid step carries the master tree in its ZeRO state."""
    import jax.numpy as jnp
    # unit check: tiny updates vanish without master, accumulate with
    p0 = jnp.full((64,), 1.0, jnp.bfloat16)
    g = jnp.full((64,), 1e-3, jnp.float32)
    for mp_flag, expect_change in ((False, False), (True, True)):
        # per-step Adam drift = lr (1e-3) < bf16 ulp at 1.0 (0.0039/2);
        # 10 accumulated steps = 0.01 > ulp — only the master survives
        opt = pt.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.0,
                                 multi_precision=mp_flag)
        init_fn, update_fn = opt.functional()
        params = {"w": p0}
        st = init_fn(params)
        for i in range(1, 11):
            params, st = update_fn({"w": g}, params, st, step=i)
        changed = not np.array_equal(np.asarray(params["w"],
                                               dtype=np.float32),
                                     np.asarray(p0, dtype=np.float32))
        assert changed == expect_change, (mp_flag, params["w"][:3])

    # hybrid integration: master tree present, step runs, params bf16
    mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
    fns, specs = make_llama_tp_fns(NH, 2)
    blocks, embed, head = init_llama_tp_params(
        L, H, F, V, rng=np.random.RandomState(141))
    to_bf16 = lambda t: jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), t)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, multi_precision=True)
    step_fn, params, opt_state, (p_sh, s_sh) = build_hybrid_train_step(
        *fns, to_bf16(blocks), to_bf16(embed), to_bf16(head), mesh, opt,
        num_micro=M, block_param_specs=specs[0],
        embed_param_specs=specs[1], head_param_specs=specs[2],
        zero_stage=1)
    assert "master" in opt_state
    assert opt_state["master"]["blocks"]["wq"].dtype == jnp.float32
    rng = np.random.RandomState(142)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    loss, params, opt_state = step_fn(params, opt_state, ids, ids, 1)
    assert np.isfinite(float(loss))
    assert params["blocks"]["wq"].dtype == jnp.bfloat16


def test_multi_precision_checkpoint_guard():
    """code-review r4: a multi_precision optimizer must refuse a
    checkpoint saved without masters instead of silently degrading."""
    import pytest
    opt = pt.optimizer.AdamW(learning_rate=1e-3, multi_precision=True)
    with pytest.raises(ValueError, match="master"):
        opt.set_state_dict({"step": 5, "state": {"m": {}, "v": {}}})


def test_moe_sorted_dispatch_matches_dense():
    """dispatch="sorted" (reference global_scatter shape: capacity bins,
    routed-token matmuls, weighted scatter-add) reproduces the dense
    GShard dispatch exactly when capacity covers every routed token —
    loss AND expert grads; the on-chip A/B
    (benchmarks/moe_dispatch_bench.py) picks the default."""
    from paddle_tpu.parallel.hybrid import (init_moe_tp_params,
                                            make_moe_tp_fns)
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    E, K = 4, 2
    rng = np.random.RandomState(95)
    ids = jnp.asarray(rng.randint(0, V, size=(B, S)).astype(np.int32))
    outs = {}
    for mode in ("dense", "sorted"):
        mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
        fns, specs = make_moe_tp_fns(
            NH, 2, num_experts=E, top_k=K, dispatch=mode,
            capacity_factor=float(E))      # C = T: nothing can drop
        blocks, embed, head = init_moe_tp_params(
            L, H, F, V, E, rng=np.random.RandomState(91))
        grad_fn, (stacked, emb_p, head_p, _s) = build_1f1b_train_step(
            *fns, blocks, embed, head, mesh, num_micro=M,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], batch_axes=("dp", "sharding"))
        loss, (d_blk, _de, _dh) = jax.jit(grad_fn)(
            stacked, emb_p, head_p, ids, ids)
        outs[mode] = (float(loss), np.asarray(d_blk["we_d"]),
                      np.asarray(d_blk["w_gate"]))
    np.testing.assert_allclose(outs["sorted"][0], outs["dense"][0],
                               rtol=1e-5)
    np.testing.assert_allclose(outs["sorted"][1], outs["dense"][1],
                               rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(outs["sorted"][2], outs["dense"][2],
                               rtol=1e-4, atol=1e-7)


def test_moe_sorted_dispatch_capacity_drops():
    """With a tight capacity the sorted dispatch DROPS overflow pairs —
    and only those: the result equals the dense combine with the same
    pairs' weights zeroed by the deterministic (stable-sort) drop rule."""
    from paddle_tpu.parallel.hybrid import (init_moe_tp_params,
                                            make_moe_tp_fns)
    from paddle_tpu.parallel.mesh import P as Pspec
    E, K, cap = 4, 2, 0.5
    mesh = dist.init_mesh(dp=1, pp=1, sharding=1, mp=2)
    fns, specs = make_moe_tp_fns(NH, 2, num_experts=E, top_k=K,
                                 dispatch="sorted", capacity_factor=cap)
    blocks, embed, head = init_moe_tp_params(
        1, H, F, V, E, rng=np.random.RandomState(97))
    block_fn = fns[0]
    rng = np.random.RandomState(98)
    x = jnp.asarray(rng.randn(2, 8, H).astype(np.float32) * 0.3)
    bp = blocks[0]

    def body(px, xx):
        return block_fn(px, xx)

    sharded_params = {
        n: jax.device_put(v, jax.NamedSharding(mesh.mesh, Pspec(*spec)))
        for (n, v), spec in zip(bp.items(),
                                [specs[0][n] for n in bp])}
    y = jax.shard_map(body, mesh=mesh.mesh,
                      in_specs=({n: specs[0][n] for n in bp},
                                Pspec()),
                      out_specs=Pspec(), check_vma=False)(
        sharded_params, x)

    # reference: dense combine with weights zeroed by the SAME drop rule
    T = 2 * 8
    C = max(1, min(int(cap * T * K / E), T))

    def rms(v, w, eps=1e-5):
        var = jnp.mean(jnp.square(v), -1, keepdims=True)
        return v * jax.lax.rsqrt(var + eps) * w

    # replicate attention half
    def attn_half(p, xx):
        mb, s, h = xx.shape
        hn = rms(xx, p["ln1"])
        q = (hn @ p["wq"]).reshape(mb, s, NH, -1)
        k = (hn @ p["wk"]).reshape(mb, s, NH, -1)
        v = (hn @ p["wv"]).reshape(mb, s, NH, -1)
        dh = q.shape[-1]
        lg = jnp.einsum("bqnd,bknd->bnqk", q, k) / np.sqrt(dh)
        mask = jnp.tril(jnp.ones((s, s), bool))
        lg = jnp.where(mask, lg, jnp.finfo(lg.dtype).min)
        a = jax.nn.softmax(lg, -1)
        ctx = jnp.einsum("bnqk,bknd->bqnd", a, v).reshape(mb, s, -1)
        return xx + ctx @ p["wo"]

    xa = attn_half(bp, x)
    hn = rms(xa, bp["ln2"])
    logits = hn @ bp["w_gate"]
    topv, topi = jax.lax.top_k(logits, K)
    probs = jax.nn.softmax(topv.astype(jnp.float32), -1)
    # drop rule: flat (token, expert) pairs in stable order per expert;
    # pair kept iff its rank within its expert's run < C
    flat_g = np.asarray(topi.reshape(-1))
    kept = np.zeros(len(flat_g), bool)
    counts = {e: 0 for e in range(E)}
    for j, e in enumerate(flat_g):
        if counts[e] < C:
            kept[j] = True
            counts[e] += 1
    comb = np.zeros((T, E), np.float32)
    pf = np.asarray(probs.reshape(-1))
    tf = np.repeat(np.arange(T), K)
    for j in range(len(flat_g)):
        if kept[j]:
            comb[tf[j], flat_g[j]] += pf[j]
    comb = jnp.asarray(comb.reshape(2, 8, E))
    up = jnp.einsum("bsh,ehf->ebsf", hn, bp["we_g"])
    up = jax.nn.silu(up) * jnp.einsum("bsh,ehf->ebsf", hn, bp["we_u"])
    down = jnp.einsum("ebsf,efh->ebsh", up, bp["we_d"])
    want = xa + jnp.einsum("ebsh,bse->bsh", down.astype(jnp.float32),
                           comb).astype(x.dtype)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # sanity: drops actually happened at this capacity
    assert kept.sum() < len(flat_g)


@pytest.mark.slow
def test_hybrid_trace_time_scales_with_stacked_blocks():
    """Compile-time canary (r4 weak #4): the stacked-scan hybrid block
    must keep TRACE+LOWER time flat in depth — the tick table scans a
    [v,S,C,...] stack, so 32 layers lower as fast as 8 (a per-layer
    unrolled builder would blow up here). Full-size compile walls are
    tracked on-chip by benchmarks/compile_hybrid.py."""
    import time
    from paddle_tpu.parallel.pp_1f1b import build_1f1b_train_step
    times = {}
    for Lc in (8, 32):
        mesh = dist.init_mesh(dp=1, pp=2, sharding=2, mp=2)
        fns, specs = make_llama_tp_fns(NH, 2)
        blocks = [jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), bp)
            for bp in init_llama_tp_params(
                Lc, H, F, V, rng=np.random.RandomState(5))[0]]
        _b, embed, head = init_llama_tp_params(
            2, H, F, V, rng=np.random.RandomState(5))
        e_avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), embed)
        h_avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), head)
        grad_fn, (stk, ep, hp, _s) = build_1f1b_train_step(
            *fns, blocks, e_avals, h_avals, mesh, num_micro=2,
            block_param_specs=specs[0], embed_param_specs=specs[1],
            head_param_specs=specs[2], batch_axes=("dp", "sharding"))
        ids = jax.ShapeDtypeStruct((8, S), jnp.int32)
        t0 = time.time()
        jax.jit(grad_fn).lower(stk, ep, hp, ids, ids)
        times[Lc] = time.time() - t0
    # depth rides the scan: 4x the layers must not cost anywhere near
    # 4x the trace+lower time (allow 2x for stack-shape overheads)
    assert times[32] < max(2.0 * times[8], times[8] + 5.0), times
