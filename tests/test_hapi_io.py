"""hapi Model.fit / io / metrics / checkpoint tests."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.io.dataloader import DistributedBatchSampler


def _toy_dataset(n=64):
    x = np.random.randn(n, 4).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
    return TensorDataset([x, y])


def test_model_fit_loss_decreases(capsys):
    ds = _toy_dataset(128)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()),
        loss=nn.BCEWithLogitsLoss())
    model.fit(ds, batch_size=16, epochs=3, verbose=0)
    res = model.evaluate(ds, batch_size=16)
    assert res["loss"][0] < 0.6


def test_model_save_load(tmp_path):
    net = nn.Linear(3, 2)
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()),
        loss=nn.MSELoss())
    p = str(tmp_path / "ckpt")
    model.save(p)
    w0 = net.weight.numpy().copy()
    net.weight.set_value(np.zeros_like(w0))
    model.load(p)
    np.testing.assert_allclose(net.weight.numpy(), w0)


def test_paddle_save_load_bf16(tmp_path):
    t = pt.to_tensor(np.random.randn(4, 4).astype(np.float32)).astype(
        pt.bfloat16)
    path = str(tmp_path / "t.pd")
    pt.save({"w": t, "meta": {"step": 3}}, path)
    back = pt.load(path)
    assert back["meta"]["step"] == 3
    assert back["w"].dtype == pt.bfloat16


def test_dataloader_batching_and_workers():
    ds = _toy_dataset(30)
    dl = DataLoader(ds, batch_size=8, drop_last=False, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (8, 4)
    assert batches[-1][0].shape == (6, 4)


def test_distributed_batch_sampler_shards():
    ds = _toy_dataset(32)
    s0 = DistributedBatchSampler(ds, batch_size=4, num_replicas=4, rank=0)
    s1 = DistributedBatchSampler(ds, batch_size=4, num_replicas=4, rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert len(i0) == len(i1) == 8
    assert not set(i0) & set(i1)


def test_metrics_accuracy():
    from paddle_tpu.metric import Accuracy
    m = Accuracy()
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = np.array([1, 0, 0])
    m.update(m.compute(pred, label))
    assert m.accumulate() == pytest.approx(2 / 3)


def test_metrics_auc_precision_recall():
    from paddle_tpu.metric import Auc, Precision, Recall
    preds = np.array([0.9, 0.8, 0.2, 0.1])
    labels = np.array([1, 1, 0, 0])
    p = Precision()
    p.update(preds, labels)
    assert p.accumulate() == 1.0
    r = Recall()
    r.update(preds, labels)
    assert r.accumulate() == 1.0
    a = Auc()
    a.update(preds, labels)
    assert a.accumulate() > 0.9


def test_profiler_timer_and_events():
    import paddle_tpu.profiler as prof
    p = prof.Profiler(timer_only=True)
    p.start()
    for _ in range(3):
        with prof.RecordEvent("work"):
            _ = pt.ops.ones([10]).sum()
        p.step(num_samples=4)
    p.stop()
    assert p.timer.count == 3
    assert "steps=3" in p.summary()


def test_sharded_checkpoint_roundtrip(tmp_path):
    import jax
    from paddle_tpu.io.checkpoint import save_sharded, load_sharded
    state = {"w": jax.numpy.arange(16.0).reshape(4, 4),
             "b": jax.numpy.ones((4,))}
    path = str(tmp_path / "ckpt_dir")
    save_sharded(state, path)
    back = load_sharded(path)
    np.testing.assert_allclose(np.asarray(back["w"]),
                               np.asarray(state["w"]))


def test_engine_fit_auto_parallel():
    from paddle_tpu.parallel.auto_parallel import Engine, Strategy
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    strategy = Strategy()
    engine = Engine(model=net, loss=nn.MSELoss(),
                    optimizer=pt.optimizer.Adam(
                        learning_rate=0.01, parameters=net.parameters()),
                    strategy=strategy)
    ds = _toy_dataset(64)
    logs = engine.fit(ds, batch_size=8, epochs=2, verbose=0)
    assert "loss" in logs
    ev = engine.evaluate(ds, batch_size=8)
    assert ev["eval_loss"] is not None
