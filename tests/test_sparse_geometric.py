"""Sparse + geometric package tests (numpy-oracle style, reference test
pattern: python/paddle/fluid/tests/unittests/test_sparse_*_op.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import sparse


def _rand_sparse(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    d = rng.randn(*shape).astype(np.float32)
    mask = rng.rand(*shape) < density
    return d * mask


class TestSparseCreation:
    def test_coo_roundtrip(self):
        d = _rand_sparse((4, 5))
        s = sparse.to_sparse_coo(pt.to_tensor(d))
        assert s.is_sparse_coo()
        np.testing.assert_allclose(s.to_dense().numpy(), d)
        assert s.shape == [4, 5]

    def test_coo_from_indices(self):
        idx = [[0, 1, 2], [1, 2, 0]]
        vals = [1.0, 2.0, 3.0]
        s = sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
        dense = np.zeros((3, 3), np.float32)
        dense[0, 1], dense[1, 2], dense[2, 0] = 1, 2, 3
        np.testing.assert_allclose(s.to_dense().numpy(), dense)
        assert s.nnz() == 3
        np.testing.assert_array_equal(s.indices().numpy(), np.array(idx))

    def test_csr_roundtrip(self):
        d = _rand_sparse((4, 6))
        s = sparse.to_sparse_csr(pt.to_tensor(d))
        assert s.is_sparse_csr()
        np.testing.assert_allclose(s.to_dense().numpy(), d)

    def test_csr_from_parts(self):
        crows = [0, 2, 3, 5]
        cols = [1, 3, 2, 0, 1]
        vals = [1., 2., 3., 4., 5.]
        s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 4])
        dense = np.zeros((3, 4), np.float32)
        dense[0, 1], dense[0, 3], dense[1, 2] = 1, 2, 3
        dense[2, 0], dense[2, 1] = 4, 5
        np.testing.assert_allclose(s.to_dense().numpy(), dense)

    def test_coo_csr_convert(self):
        d = _rand_sparse((5, 5))
        s = sparse.to_sparse_coo(pt.to_tensor(d))
        np.testing.assert_allclose(s.to_sparse_csr().to_dense().numpy(), d)

    def test_coalesce(self):
        idx = [[0, 0], [1, 1]]
        s = sparse.sparse_coo_tensor(idx, [1.0, 2.0], shape=[2, 2])
        c = s.coalesce()
        np.testing.assert_allclose(c.to_dense().numpy()[0, 1], 3.0)


class TestSparseMath:
    @pytest.mark.parametrize("name", ["sin", "tanh", "sqrt", "square",
                                      "log1p", "abs", "neg", "expm1"])
    def test_unary(self, name):
        d = np.abs(_rand_sparse((4, 5))) * 0.5  # sqrt/log1p domain
        s = sparse.to_sparse_coo(pt.to_tensor(d))
        out = getattr(sparse, name)(s)
        ref = getattr(np, {"neg": "negative", "abs": "abs"}.get(name, name))(d)
        np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-6)

    def test_add_subtract(self):
        a, b = _rand_sparse((3, 4), seed=1), _rand_sparse((3, 4), seed=2)
        sa = sparse.to_sparse_coo(pt.to_tensor(a))
        sb = sparse.to_sparse_coo(pt.to_tensor(b))
        np.testing.assert_allclose(
            sparse.add(sa, sb).to_dense().numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose(
            sparse.subtract(sa, sb).to_dense().numpy(), a - b, rtol=1e-6)

    def test_multiply_divide(self):
        a, b = _rand_sparse((3, 4), seed=1), _rand_sparse((3, 4), seed=2)
        sa = sparse.to_sparse_coo(pt.to_tensor(a))
        sb = sparse.to_sparse_coo(pt.to_tensor(b))
        np.testing.assert_allclose(
            sparse.multiply(sa, sb).to_dense().numpy(), a * b, rtol=1e-6)
        got = sparse.divide(sa, sb).to_dense().numpy()
        ref = np.where(b == 0, 0, a / np.where(b == 0, 1, b))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_matmul_sparse_dense(self):
        a = _rand_sparse((4, 6))
        b = np.random.RandomState(3).randn(6, 5).astype(np.float32)
        s = sparse.to_sparse_coo(pt.to_tensor(a))
        out = sparse.matmul(s, pt.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5, atol=1e-5)

    def test_matmul_csr(self):
        a = _rand_sparse((4, 6))
        b = np.random.RandomState(3).randn(6, 5).astype(np.float32)
        s = sparse.to_sparse_csr(pt.to_tensor(a))
        out = sparse.matmul(s, pt.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5, atol=1e-5)

    def test_masked_matmul(self):
        rng = np.random.RandomState(0)
        a = rng.randn(4, 6).astype(np.float32)
        b = rng.randn(6, 4).astype(np.float32)
        mask = _rand_sparse((4, 4), seed=5)
        sm = sparse.to_sparse_coo(pt.to_tensor(mask))
        out = sparse.masked_matmul(pt.to_tensor(a), pt.to_tensor(b), sm)
        ref = (a @ b) * (mask != 0)
        np.testing.assert_allclose(out.to_dense().numpy(), ref,
                                   rtol=1e-5, atol=1e-5)

    def test_mv(self):
        a = _rand_sparse((4, 6))
        v = np.random.RandomState(1).randn(6).astype(np.float32)
        s = sparse.to_sparse_coo(pt.to_tensor(a))
        np.testing.assert_allclose(sparse.mv(s, pt.to_tensor(v)).numpy(),
                                   a @ v, rtol=1e-5, atol=1e-5)

    def test_addmm(self):
        rng = np.random.RandomState(0)
        inp = rng.randn(4, 5).astype(np.float32)
        x = _rand_sparse((4, 6))
        y = rng.randn(6, 5).astype(np.float32)
        s = sparse.to_sparse_coo(pt.to_tensor(x))
        out = sparse.addmm(pt.to_tensor(inp), s, pt.to_tensor(y),
                           beta=2.0, alpha=0.5)
        np.testing.assert_allclose(out.numpy(), 2.0 * inp + 0.5 * (x @ y),
                                   rtol=1e-5, atol=1e-5)

    def test_transpose_reshape(self):
        d = _rand_sparse((3, 4))
        s = sparse.to_sparse_coo(pt.to_tensor(d))
        np.testing.assert_allclose(
            sparse.transpose(s, [1, 0]).to_dense().numpy(), d.T)
        np.testing.assert_allclose(
            sparse.reshape(s, [4, 3]).to_dense().numpy(), d.reshape(4, 3))

    def test_is_same_shape_cast(self):
        d = _rand_sparse((3, 4))
        s = sparse.to_sparse_coo(pt.to_tensor(d))
        assert sparse.is_same_shape(s, s)
        c = sparse.cast(s, value_dtype="float16")
        assert c.dtype == np.float16


class TestSparseNN:
    def test_relu_softmax(self):
        d = _rand_sparse((4, 5))
        s = sparse.to_sparse_csr(pt.to_tensor(d))
        r = sparse.nn.functional.relu(s)
        np.testing.assert_allclose(r.to_dense().numpy(), np.maximum(d, 0))
        sm = sparse.nn.functional.softmax(s)
        got = sm.to_dense().numpy()
        for i in range(4):
            nz = d[i] != 0
            if nz.any():
                e = np.exp(d[i][nz] - d[i][nz].max())
                np.testing.assert_allclose(got[i][nz], e / e.sum(),
                                           rtol=1e-5)

    def test_conv3d(self):
        rng = np.random.RandomState(0)
        x = _rand_sparse((1, 4, 4, 4, 2), density=0.4)
        s = sparse.to_sparse_coo(pt.to_tensor(x), 4)
        conv = sparse.nn.Conv3D(2, 3, kernel_size=3, padding=1)
        out = conv(s)
        assert out.shape == [1, 4, 4, 4, 3]

    def test_subm_conv3d_preserves_sparsity(self):
        x = _rand_sparse((1, 4, 4, 4, 2), density=0.3)
        s = sparse.to_sparse_coo(pt.to_tensor(x), 4)
        conv = sparse.nn.SubmConv3D(2, 3, kernel_size=3, padding=1)
        out = conv(s).to_dense().numpy()
        inactive = ~np.any(x != 0, axis=-1)
        assert np.all(out[inactive] == 0)

    def test_maxpool3d(self):
        x = np.abs(_rand_sparse((1, 4, 4, 4, 2), density=0.5))
        s = sparse.to_sparse_coo(pt.to_tensor(x), 4)
        out = sparse.nn.MaxPool3D(kernel_size=2, stride=2)(s)
        assert out.shape == [1, 2, 2, 2, 2]
        import jax.numpy as jnp  # oracle via strided max
        ref = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(2, 4, 6))
        np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-6)

    def test_batchnorm(self):
        x = _rand_sparse((1, 4, 4, 4, 3), density=0.5)
        s = sparse.to_sparse_coo(pt.to_tensor(x), 4)
        bn = sparse.nn.BatchNorm(3)
        bn.train()
        out = bn(s)
        assert out.shape == [1, 4, 4, 4, 3]

    def test_attention(self):
        rng = np.random.RandomState(0)
        q = rng.randn(2, 2, 8, 4).astype(np.float32)
        k = rng.randn(2, 2, 8, 4).astype(np.float32)
        v = rng.randn(2, 2, 8, 4).astype(np.float32)
        mask = (rng.rand(8, 8) < 0.6).astype(np.float32)
        mask[:, 0] = 1  # every query attends to something
        sm = sparse.to_sparse_csr(pt.to_tensor(mask))
        out = sparse.nn.functional.attention(
            pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v), sm)
        assert out.shape == [2, 2, 8, 4]
        # oracle
        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(4)
        scores = np.where(mask != 0, scores, -1e9)
        e = np.exp(scores - scores.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        p = np.where(mask != 0, p, 0)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


class TestGeometric:
    def test_segment_ops(self):
        from paddle_tpu import geometric as G
        data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
        ids = np.array([0, 0, 1, 2])
        d, i = pt.to_tensor(data), pt.to_tensor(ids)
        np.testing.assert_allclose(G.segment_sum(d, i).numpy(),
                                   [[4, 6], [5, 6], [7, 8]])
        np.testing.assert_allclose(G.segment_mean(d, i).numpy(),
                                   [[2, 3], [5, 6], [7, 8]])
        np.testing.assert_allclose(G.segment_min(d, i).numpy(),
                                   [[1, 2], [5, 6], [7, 8]])
        np.testing.assert_allclose(G.segment_max(d, i).numpy(),
                                   [[3, 4], [5, 6], [7, 8]])

    def test_send_u_recv(self):
        from paddle_tpu import geometric as G
        x = np.array([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]], np.float32)
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 1, 0])
        out = G.send_u_recv(pt.to_tensor(x), pt.to_tensor(src),
                            pt.to_tensor(dst), reduce_op="sum")
        ref = np.zeros_like(x)
        for s, d in zip(src, dst):
            ref[d] += x[s]
        np.testing.assert_allclose(out.numpy(), ref)
        out = G.send_u_recv(pt.to_tensor(x), pt.to_tensor(src),
                            pt.to_tensor(dst), reduce_op="max")
        ref = np.full_like(x, -np.inf)
        for s, d in zip(src, dst):
            ref[d] = np.maximum(ref[d], x[s])
        ref[np.isinf(ref)] = 0
        np.testing.assert_allclose(out.numpy(), ref)

    def test_send_ue_recv_and_uv(self):
        from paddle_tpu import geometric as G
        x = np.array([[1., 2.], [3., 4.], [5., 6.]], np.float32)
        y = np.array([[10., 10.], [20., 20.], [30., 30.], [40., 40.]],
                     np.float32)
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 0, 2])
        out = G.send_ue_recv(pt.to_tensor(x), pt.to_tensor(y),
                             pt.to_tensor(src), pt.to_tensor(dst),
                             message_op="add", reduce_op="sum")
        ref = np.zeros_like(x)
        for e, (s, d) in enumerate(zip(src, dst)):
            ref[d] += x[s] + y[e]
        np.testing.assert_allclose(out.numpy(), ref)
        out = G.send_uv(pt.to_tensor(x), pt.to_tensor(x), pt.to_tensor(src),
                        pt.to_tensor(dst), message_op="mul")
        np.testing.assert_allclose(out.numpy(), x[src] * x[dst])

    def test_reindex_graph(self):
        from paddle_tpu import geometric as G
        x = np.array([0, 5, 8])
        neighbors = np.array([8, 9, 0, 4, 7, 6, 7], dtype=np.int64)
        count = np.array([2, 3, 2], dtype=np.int32)
        src, dst, nodes = G.reindex_graph(pt.to_tensor(x),
                                          pt.to_tensor(neighbors),
                                          pt.to_tensor(count))
        nodes_np = nodes.numpy()
        assert list(nodes_np[:3]) == [0, 5, 8]
        # src maps each neighbor to its local id
        np.testing.assert_array_equal(
            nodes_np[src.numpy()], neighbors)
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])

    def test_sample_neighbors(self):
        from paddle_tpu import geometric as G
        # CSC: col j's rows at row[colptr[j]:colptr[j+1]]
        row = np.array([1, 2, 3, 0, 2, 0, 1], dtype=np.int64)
        colptr = np.array([0, 3, 5, 7, 7], dtype=np.int64)
        nodes = np.array([0, 1, 3], dtype=np.int64)
        nb, cnt = G.sample_neighbors(pt.to_tensor(row), pt.to_tensor(colptr),
                                     pt.to_tensor(nodes), sample_size=2)
        cnt_np = cnt.numpy()
        assert cnt_np[0] == 2 and cnt_np[1] == 2 and cnt_np[2] == 0
        assert set(nb.numpy()[:2]).issubset({1, 2, 3})


class TestReviewRegressions2:
    def test_reindex_heter_graph_two_edge_types(self):
        from paddle_tpu import geometric as G
        x = np.array([0, 5])
        nb1, c1 = np.array([5, 0], np.int64), np.array([1, 1], np.int32)
        nb2, c2 = np.array([7, 0], np.int64), np.array([1, 1], np.int32)
        src, dst, nodes = G.reindex_heter_graph(
            pt.to_tensor(x), [pt.to_tensor(nb1), pt.to_tensor(nb2)],
            [pt.to_tensor(c1), pt.to_tensor(c2)])
        nodes_np = nodes.numpy()
        assert list(nodes_np[:2]) == [0, 5]
        np.testing.assert_array_equal(
            nodes_np[src.numpy()], np.concatenate([nb1, nb2]))
        np.testing.assert_array_equal(dst.numpy(), [0, 1, 0, 1])

    def test_batched_sparse_matmul(self):
        rng = np.random.RandomState(0)
        a = _rand_sparse((2, 4, 6))
        b = rng.randn(2, 6, 5).astype(np.float32)
        s = sparse.to_sparse_coo(pt.to_tensor(a))
        out = sparse.matmul(s, pt.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5, atol=1e-5)

    def test_batched_masked_matmul(self):
        rng = np.random.RandomState(0)
        a = rng.randn(2, 4, 6).astype(np.float32)
        b = rng.randn(2, 6, 4).astype(np.float32)
        mask = _rand_sparse((2, 4, 4), seed=5)
        sm = sparse.to_sparse_coo(pt.to_tensor(mask))
        out = sparse.masked_matmul(pt.to_tensor(a), pt.to_tensor(b), sm)
        ref = (a @ b) * (mask != 0)
        np.testing.assert_allclose(out.to_dense().numpy(), ref,
                                   rtol=1e-5, atol=1e-5)

    def test_export_independent_dynamic_dims(self):
        import os.path as osp
        import tempfile
        from paddle_tpu import static as st
        from paddle_tpu.ops.registry import OPS
        prog, sprog = st.Program(), st.Program()
        with st.program_guard(prog, sprog):
            x = st.data("xd1", [-1, 4])
            z = st.data("xd2", [-1, 4])
            w = st.create_parameter([4, 2], name="w_dyn2")
            y1 = OPS["matmul"](x, w)
            y2 = OPS["matmul"](z, w)
        exe = st.Executor()
        exe.run(sprog)
        d = tempfile.mkdtemp()
        st.save_inference_model(osp.join(d, "m"), [x, z], [y1, y2], exe,
                                program=prog)
        from paddle_tpu.inference.export import load_exported
        prog2, feeds, _ = load_exported(osp.join(d, "m"))
        # different batch sizes per feed must be accepted (independent dims)
        out = prog2(np.ones((8, 4), np.float32),
                    np.ones((3, 4), np.float32))
        assert np.asarray(out[0]).shape == (8, 2)
        assert np.asarray(out[1]).shape == (3, 2)
