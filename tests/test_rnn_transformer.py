"""RNN/LSTM/GRU + Transformer layer tests — numpy-oracle + shape/grad.

Mirrors the reference's test strategy for rnn/transformer layers
(python/paddle/fluid/tests/unittests/test_rnn_*.py, test_transformer_api.py):
cell step vs numpy recurrence, full-sequence scan vs per-step loop,
bidirectional concat, masks, cache decode, gradient flow.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def _np(t):
    return np.asarray(t.numpy(), dtype=np.float64)


class TestCells:
    def test_simple_rnn_cell_oracle(self):
        cell = nn.SimpleRNNCell(4, 6)
        x = pt.randn([3, 4])
        h = pt.randn([3, 6])
        out, new_h = cell(x, h)
        wi, wh = _np(cell.weight_ih), _np(cell.weight_hh)
        bi, bh = _np(cell.bias_ih), _np(cell.bias_hh)
        ref = np.tanh(_np(x) @ wi.T + bi + _np(h) @ wh.T + bh)
        np.testing.assert_allclose(_np(out), ref, atol=1e-5)
        np.testing.assert_allclose(_np(new_h), ref, atol=1e-5)

    def test_lstm_cell_oracle(self):
        cell = nn.LSTMCell(4, 5)
        x, h, c = pt.randn([2, 4]), pt.randn([2, 5]), pt.randn([2, 5])
        out, (h2, c2) = cell(x, (h, c))
        gates = (_np(x) @ _np(cell.weight_ih).T + _np(cell.bias_ih)
                 + _np(h) @ _np(cell.weight_hh).T + _np(cell.bias_hh))
        i, f, g, o = np.split(gates, 4, axis=-1)
        sig = lambda v: 1 / (1 + np.exp(-v))
        c_ref = sig(f) * _np(c) + sig(i) * np.tanh(g)
        h_ref = sig(o) * np.tanh(c_ref)
        np.testing.assert_allclose(_np(h2), h_ref, atol=1e-5)
        np.testing.assert_allclose(_np(c2), c_ref, atol=1e-5)
        np.testing.assert_allclose(_np(out), h_ref, atol=1e-5)

    def test_gru_cell_oracle(self):
        cell = nn.GRUCell(3, 4)
        x, h = pt.randn([2, 3]), pt.randn([2, 4])
        out, _ = cell(x, h)
        xg = _np(x) @ _np(cell.weight_ih).T + _np(cell.bias_ih)
        hg = _np(h) @ _np(cell.weight_hh).T + _np(cell.bias_hh)
        x_r, x_z, x_c = np.split(xg, 3, -1)
        h_r, h_z, h_c = np.split(hg, 3, -1)
        sig = lambda v: 1 / (1 + np.exp(-v))
        r, z = sig(x_r + h_r), sig(x_z + h_z)
        cand = np.tanh(x_c + r * h_c)
        ref = z * _np(h) + (1 - z) * cand
        np.testing.assert_allclose(_np(out), ref, atol=1e-5)


class TestRNNWrappers:
    def test_scan_matches_stepwise(self):
        cell = nn.LSTMCell(4, 5)
        rnn = nn.RNN(cell)
        x = pt.randn([2, 7, 4])
        outs, (hf, cf) = rnn(x)
        # per-step loop oracle
        h = pt.zeros([2, 5])
        c = pt.zeros([2, 5])
        step_outs = []
        for t in range(7):
            o, (h, c) = cell(pt.to_tensor(x.numpy()[:, t]), (h, c))
            step_outs.append(o.numpy())
        ref = np.stack(step_outs, axis=1)
        np.testing.assert_allclose(outs.numpy(), ref, atol=1e-5)
        np.testing.assert_allclose(hf.numpy(), h.numpy(), atol=1e-5)
        np.testing.assert_allclose(cf.numpy(), c.numpy(), atol=1e-5)

    def test_sequence_length_masks(self):
        cell = nn.GRUCell(3, 4)
        rnn = nn.RNN(cell)
        x = pt.randn([2, 6, 3])
        sl = pt.to_tensor(np.array([4, 6], dtype=np.int32))
        outs, fin = rnn(x, sequence_length=sl)
        o = outs.numpy()
        assert np.allclose(o[0, 4:], 0.0)
        assert not np.allclose(o[1, 5], 0.0)
        # final state of row 0 equals state at t=3
        outs_full, _ = rnn(x)
        np.testing.assert_allclose(fin.numpy()[0], outs_full.numpy()[0, 3],
                                   atol=1e-5)

    def test_birnn_and_stacked(self):
        lstm = nn.LSTM(8, 16, num_layers=2, direction="bidirect")
        x = pt.randn([3, 5, 8])
        outs, (h, c) = lstm(x)
        assert list(outs.shape) == [3, 5, 32]
        assert list(h.shape) == [4, 3, 16] and list(c.shape) == [4, 3, 16]

        birnn = nn.BiRNN(nn.SimpleRNNCell(8, 6), nn.SimpleRNNCell(8, 6))
        o2, (ff, fb) = birnn(x)
        assert list(o2.shape) == [3, 5, 12]

    def test_gru_layer_shapes_and_grad(self):
        gru = nn.GRU(4, 8, num_layers=1)
        x = pt.randn([2, 5, 4])
        x.stop_gradient = False
        outs, h = gru(x)
        assert list(outs.shape) == [2, 5, 8]
        assert list(h.shape) == [1, 2, 8]
        loss = outs.sum()
        loss.backward()
        assert gru._cells[0].weight_ih.grad is not None
        assert np.isfinite(gru._cells[0].weight_ih.grad.numpy()).all()

    def test_time_major(self):
        rnn = nn.SimpleRNN(4, 6, time_major=True)
        x = pt.randn([5, 2, 4])  # [T,B,C]
        outs, h = rnn(x)
        assert list(outs.shape) == [5, 2, 6]


class TestTransformer:
    def test_mha_self_attention_oracle(self):
        mha = nn.MultiHeadAttention(16, 4)
        mha.eval()
        x = pt.randn([2, 5, 16])
        out = mha(x)
        assert list(out.shape) == [2, 5, 16]
        # oracle: project, per-head softmax attention, out-project
        q = _np(x) @ _np(mha.q_proj.weight) + _np(mha.q_proj.bias)
        k = _np(x) @ _np(mha.k_proj.weight) + _np(mha.k_proj.bias)
        v = _np(x) @ _np(mha.v_proj.weight) + _np(mha.v_proj.bias)
        B, S, H, D = 2, 5, 4, 4
        q = q.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        s = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        o = (p @ v).transpose(0, 2, 1, 3).reshape(B, S, 16)
        ref = o @ _np(mha.out_proj.weight) + _np(mha.out_proj.bias)
        np.testing.assert_allclose(out.numpy(), ref, atol=2e-3, rtol=2e-3)

    def test_mha_bool_and_float_mask_agree(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = pt.randn([1, 4, 8])
        keep = np.tril(np.ones((1, 1, 4, 4), dtype=bool))
        out_b = mha(x, attn_mask=pt.to_tensor(keep))
        fmask = np.where(keep, 0.0, -1e9).astype(np.float32)
        out_f = mha(x, attn_mask=pt.to_tensor(fmask))
        np.testing.assert_allclose(out_b.numpy(), out_f.numpy(), atol=1e-5)

    def test_mha_cache_decode_matches_full(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = pt.randn([1, 6, 8])
        causal = np.tril(np.ones((1, 1, 6, 6), dtype=bool))
        full = mha(x, attn_mask=pt.to_tensor(causal)).numpy()
        cache = mha.gen_cache(pt.zeros([1, 0, 8]))
        step_outs = []
        for t in range(6):
            xt = pt.to_tensor(x.numpy()[:, t:t + 1])
            o, cache = mha(xt, xt, xt, None, cache)
            step_outs.append(o.numpy())
        inc = np.concatenate(step_outs, axis=1)
        np.testing.assert_allclose(inc, full, atol=1e-4, rtol=1e-4)

    def test_encoder_decoder_shapes(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32,
                               dropout=0.0)
        model.eval()
        src = pt.randn([2, 6, 16])
        tgt = pt.randn([2, 4, 16])
        out = model(src, tgt)
        assert list(out.shape) == [2, 4, 16]
        m = model.generate_square_subsequent_mask(4)
        assert list(m.shape) == [4, 4]
        out2 = model(src, tgt, tgt_mask=m)
        assert np.isfinite(out2.numpy()).all()

    def test_encoder_layers_independent_params(self):
        enc_layer = nn.TransformerEncoderLayer(8, 2, 16, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 3)
        names = [n for n, _ in enc.named_parameters()]
        assert len(names) == len(set(names))
        assert len(names) == 3 * 16  # 4 attn linears + 2 ffn + 2 ln, w+b

    def test_encoder_grad_flows(self):
        enc_layer = nn.TransformerEncoderLayer(8, 2, 16, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 2)
        x = pt.randn([2, 3, 8])
        out = enc(x)
        out.sum().backward()
        for n, p in enc.named_parameters():
            assert p.grad is not None, n


class TestReviewRegressions:
    def test_lstm_list_initial_states(self):
        lstm = nn.LSTM(4, 8)
        x = pt.randn([2, 5, 4])
        h0, c0 = pt.zeros([1, 2, 8]), pt.zeros([1, 2, 8])
        out_t, _ = lstm(x, (h0, c0))
        out_l, _ = lstm(x, [h0, c0])
        np.testing.assert_allclose(out_t.numpy(), out_l.numpy())

    def test_gen_cache_seeded_with_kv(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = pt.randn([1, 3, 8])
        k0 = pt.randn([1, 2, 2, 4])
        v0 = pt.randn([1, 2, 2, 4])
        cache = mha.gen_cache(k0, v0)
        assert isinstance(cache, nn.MultiHeadAttention.Cache)
        o, cache2 = mha(x, x, x, None, cache)
        assert list(cache2.k.shape) == [1, 5, 2, 4]

    def test_unknown_activation_raises(self):
        with pytest.raises(ValueError):
            nn.TransformerEncoderLayer(8, 2, 16, activation="not_an_act")
