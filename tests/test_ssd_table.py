"""SSD (disk-backed) sparse table (reference ssd_sparse_table.h
capability: embedding tables larger than the in-memory cache)."""
import numpy as np

from paddle_tpu.parallel.ps import SSDSparseTable


def test_eviction_preserves_values(tmp_path):
    t = SSDSparseTable("emb", dim=4, path=str(tmp_path / "t.db"),
                       cache_rows=8, initializer="uniform", seed=0)
    ids = np.arange(64)
    first = t.pull(ids)               # 64 rows through an 8-row cache
    assert len(t.rows) <= 8
    again = t.pull(ids)
    np.testing.assert_allclose(again, first)  # values survived eviction
    t.close()


def test_push_grad_under_eviction(tmp_path):
    t = SSDSparseTable("emb", dim=2, path=str(tmp_path / "t.db"),
                       cache_rows=4, initializer="zeros", lr=1.0)
    ids = np.arange(16)
    g = np.ones((16, 2), np.float32)
    t.push_grad(ids, g)
    t.push_grad(ids, g)               # second pass reloads evicted rows
    out = t.pull(ids)
    np.testing.assert_allclose(out, -2.0)
    assert t.num_rows() == 16
    t.close()


def test_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "t.db")
    t = SSDSparseTable("emb", dim=3, path=path, cache_rows=2, lr=0.5)
    t.push_grad([1, 2, 3], np.ones((3, 3), np.float32))
    t.close()
    t2 = SSDSparseTable("emb", dim=3, path=path, cache_rows=2)
    np.testing.assert_allclose(t2.pull([1, 2, 3]), -0.5)
    t2.close()


def test_shrink_and_state_roundtrip(tmp_path):
    t = SSDSparseTable("emb", dim=2, path=str(tmp_path / "t.db"),
                       cache_rows=4, lr=1.0)
    t.push_grad(np.arange(10), np.ones((10, 2), np.float32))
    t.shrink(keep_ids=[0, 1, 2])
    assert t.num_rows() == 3
    st = t.state()
    assert list(st["ids"]) == [0, 1, 2]
    t2 = SSDSparseTable("emb2", dim=2, path=str(tmp_path / "t2.db"),
                        cache_rows=4)
    t2.load_state(st)
    np.testing.assert_allclose(t2.pull([0, 1, 2]), -1.0)
    t.close()
    t2.close()


def test_server_creates_ssd_table(tmp_path):
    from paddle_tpu.parallel.ps import PSServer
    srv = PSServer(0, 1)
    srv.create_table("big", 8, table_type="ssd",
                     path=str(tmp_path / "srv.db"), cache_rows=4)
    assert isinstance(srv.tables["big"], SSDSparseTable)
    out = srv.pull_sparse("big", np.arange(12))
    assert out.shape == (12, 8)
