"""Training chaos suite: kill the run at EVERY injected point and
prove the fault-tolerance contract (extends test_chaos.py's serving
patterns to training):

- a save killed mid-write / at the commit rename NEVER yields a loadable
  half-checkpoint: restore always lands on a checksum-valid checkpoint;
- a run resumed after any such kill bit-matches the uninterrupted
  same-seed run's per-step losses (the acceptance criterion);
- same seed => identical injection trace AND identical training
  trajectory;
- transient step/data faults are retried invisibly — the loss
  trajectory is unchanged.

Everything is numpy-step or tiny-Linear based with zero-delay retry
policies — no sleeps, tier-1 fast."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.io import TensorDataset
from paddle_tpu.reliability import (FaultInjector, ReliabilityError,
                                    ResumableLoader, RetryPolicy,
                                    TrainSupervisor, faults,
                                    verify_checkpoint)

pytestmark = pytest.mark.chaos

MAX_STEPS = 8
SAVE_EVERY = 2


def _loader():
    return ResumableLoader(list(np.arange(10, dtype=np.float64)),
                           batch_size=3, shuffle=True, seed=5)


def _step(s, b):
    m = float(np.mean(b))
    return s * 0.9 + 0.01 * m, s * 0.95 + 0.01 * m


def _sup(d, injector=None):
    return TrainSupervisor(d, save_interval_steps=SAVE_EVERY,
                           injector=injector,
                           retry=RetryPolicy(base_delay_s=0.0, jitter=0.0),
                           max_step_retries=100)


def _baseline(tmp_path):
    rep = _sup(str(tmp_path / "baseline")).run(_step, 1.0, _loader(),
                                               max_steps=MAX_STEPS)
    assert rep.status == "completed"
    return dict(rep.losses), rep.final_state


def _count_visits(tmp_path, point):
    """Visits to ``point`` in one clean run (defines the kill sweep)."""
    fi = FaultInjector(seed=0, enabled=False).on(point, probability=0.0)
    _sup(str(tmp_path / "probe"), injector=fi).run(
        _step, 1.0, _loader(), max_steps=MAX_STEPS)
    return fi.visits(point)


class TestKillAtEveryInjectedPoint:
    """THE acceptance test: for every single visit to ckpt.write and
    ckpt.rename in the training run, kill the process there; restore
    must land on a checksum-valid checkpoint and the resumed run's
    losses must bit-match the uninterrupted run."""

    @pytest.mark.parametrize("point", [faults.CKPT_WRITE,
                                       faults.CKPT_RENAME,
                                       faults.CKPT_SWAP])
    def test_kill_sweep_restores_valid_and_bit_matches(self, tmp_path,
                                                       point):
        truth, final_truth = _baseline(tmp_path)
        n = _count_visits(tmp_path, point)
        # ckpt.swap only fires on overwrite saves (the final force-save
        # re-commits the interval-saved step) — fewer visits by design
        floor = 1 if point == faults.CKPT_SWAP else 4
        assert n >= floor, f"too few {point} visits to sweep meaningfully"
        for kill_at in range(n):
            d = str(tmp_path / f"kill_{point.replace('.', '_')}_{kill_at}")
            fi = FaultInjector(seed=0).on(point, schedule=[kill_at])
            with pytest.raises(ReliabilityError):
                _sup(d, injector=fi).run(_step, 1.0, _loader(),
                                         max_steps=MAX_STEPS)
            # whatever survived on disk, the newest VALID checkpoint
            # loads cleanly (verify re-hashes every file); a kill
            # during the FIRST save legitimately leaves nothing — but
            # then nothing half-written is visible either
            sup2 = _sup(d)
            state, meta, got = sup2.store.restore()
            if got is None:
                assert sup2.store.all_steps() == [], \
                    f"kill at {point}#{kill_at}: torn dir became visible"
            else:
                verify_checkpoint(sup2.store.step_path(got))
            # exact resume: every committed step bit-matches the truth
            rep = sup2.run(_step, 1.0, _loader(), max_steps=MAX_STEPS)
            assert rep.status == "completed"
            for s, loss in rep.losses:
                assert truth[s] == loss, \
                    f"kill at {point}#{kill_at}: step {s} diverged"
            assert rep.final_state == final_truth, \
                f"kill at {point}#{kill_at}: final state diverged"

    def test_kill_rate_storm_still_converges(self, tmp_path):
        """Random kills at 30% per checkpoint write: keep resuming
        until done; the final state still bit-matches."""
        truth, final_truth = _baseline(tmp_path)
        d = str(tmp_path / "storm")
        seed = 77
        for attempt in range(50):
            fi = FaultInjector(seed=seed + attempt).on(
                faults.CKPT_WRITE, probability=0.3)
            try:
                rep = _sup(d, injector=fi).run(_step, 1.0, _loader(),
                                               max_steps=MAX_STEPS)
            except ReliabilityError:
                continue                          # died again; resume
            assert rep.status == "completed"
            break
        else:
            pytest.fail("storm never let the run finish")
        assert rep.final_state == final_truth
        for s, loss in rep.losses:
            assert truth[s] == loss


class TestChaosDeterminism:
    def test_same_seed_identical_trace_and_trajectory(self, tmp_path):
        """Satellite acceptance: same seed => identical injection trace
        and identical training results."""
        def run_once(tag):
            fi = (FaultInjector(seed=4242)
                  .on(faults.TRAIN_STEP, probability=0.25)
                  .on(faults.DATA_NEXT, probability=0.15))
            rep = _sup(str(tmp_path / tag), injector=fi).run(
                _step, 1.0, _loader(), max_steps=MAX_STEPS)
            return list(fi.trace), rep.losses, rep.saved_steps, \
                rep.retries

        a, b = run_once("a"), run_once("b")
        assert a == b
        assert a[0], "deterministic chaos run injected nothing"

    def test_injector_reset_replays_training_script(self, tmp_path):
        fi = FaultInjector(seed=9).on(faults.TRAIN_STEP, probability=0.3)

        def run(tag):
            rep = _sup(str(tmp_path / tag), injector=fi).run(
                _step, 1.0, _loader(), max_steps=MAX_STEPS)
            return list(fi.trace), rep.losses

        first = run("a")
        fi.reset()
        assert run("b") == first

    def test_transient_faults_do_not_perturb_trajectory(self, tmp_path):
        truth, final_truth = _baseline(tmp_path)
        fi = (FaultInjector(seed=31)
              .on(faults.TRAIN_STEP, probability=0.3)
              .on(faults.DATA_NEXT, probability=0.2))
        rep = _sup(str(tmp_path / "chaos"), injector=fi).run(
            _step, 1.0, _loader(), max_steps=MAX_STEPS)
        assert rep.retries > 0, "chaos never fired; raise rates"
        assert dict(rep.losses) == truth
        assert rep.final_state == final_truth


class TestFitChaos:
    """Chaos through the hapi path: a compiled guarded step under
    injected faults and checkpoint kills."""

    def _model(self):
        pt.seed(7)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
        m = pt.Model(net)
        m.prepare(optimizer=pt.optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()),
            loss=nn.BCEWithLogitsLoss())
        return m

    def _dataset(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
        return TensorDataset([x, y])

    class _Rec:
        def __init__(self):
            self.losses = []

        def set_model(self, m):
            pass

        def __getattr__(self, name):
            if name.startswith("on_"):
                return lambda *a, **k: None
            raise AttributeError(name)

        def on_train_batch_end(self, step, logs=None):
            self.losses.append(logs["loss"])

    def _fit(self, d, injector=None, rec=None):
        sup = TrainSupervisor(d, save_interval_steps=3, injector=injector,
                              retry=RetryPolicy(base_delay_s=0.0,
                                                jitter=0.0),
                              max_step_retries=100)
        rec = rec if rec is not None else self._Rec()
        self._model().fit(self._dataset(), batch_size=8, epochs=2,
                          verbose=0, callbacks=[rec], supervisor=sup)
        return rec.losses

    def test_step_fault_storm_trajectory_unchanged(self, tmp_path):
        clean = self._fit(str(tmp_path / "clean"))
        fi = FaultInjector(seed=13).on(faults.TRAIN_STEP, probability=0.25)
        chaotic = self._fit(str(tmp_path / "chaos"), injector=fi)
        assert fi.fired() > 0, "chaos never fired; raise rates"
        assert chaotic == clean

    def test_ckpt_kill_mid_fit_resumes_bit_exact(self, tmp_path):
        clean = self._fit(str(tmp_path / "clean"))
        d = str(tmp_path / "killed")
        # die at the 2nd checkpoint's commit rename
        fi = FaultInjector(seed=0).on(faults.CKPT_RENAME, schedule=[1])
        rec1 = self._Rec()
        with pytest.raises(ReliabilityError):
            self._fit(d, injector=fi, rec=rec1)
        rec2 = self._Rec()
        self._fit(d, rec=rec2)
        # the resumed tail bit-matches; nothing was lost or doubled
        assert rec2.losses == clean[len(clean) - len(rec2.losses):]
        assert rec1.losses == clean[:len(rec1.losses)]
