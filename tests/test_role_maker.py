"""Role maker env parsing (reference fleet/base/role_maker.py tests)."""
import numpy as np

from paddle_tpu.parallel.role_maker import (PaddleCloudRoleMaker, Role,
                                            UserDefinedRoleMaker)


def test_collective_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                       "10.0.0.1:6170,10.0.0.2:6170,10.0.0.3:6170")
    rm = PaddleCloudRoleMaker(is_collective=True)
    assert rm.is_worker() and not rm.is_server()
    assert rm.worker_index() == 2
    assert rm.worker_num() == 3
    assert not rm.is_first_worker()
    assert rm.get_local_endpoint() == "10.0.0.3:6170"


def test_ps_server_env(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       "10.0.0.9:7164,10.0.0.10:7164")
    monkeypatch.setenv("POD_IP", "10.0.0.10")
    monkeypatch.setenv("PADDLE_PORT", "7164")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    rm = PaddleCloudRoleMaker(is_collective=False)
    assert rm.is_server()
    assert rm.server_index() == 1
    assert rm.server_num() == 2
    assert rm.worker_num() == 4
    assert rm.worker_index() == -1


def test_ps_trainer_env(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "10.0.0.9:7164")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
    rm = PaddleCloudRoleMaker(is_collective=False)
    assert rm.is_worker()
    assert rm.worker_index() == 1
    assert rm.get_pserver_endpoints() == ["10.0.0.9:7164"]


def test_user_defined():
    rm = UserDefinedRoleMaker(
        is_collective=True, current_id=0, role=Role.WORKER,
        worker_endpoints=["127.0.0.1:1", "127.0.0.1:2"])
    assert rm.is_first_worker()
    assert rm.worker_num() == 2


def test_fleet_init_uses_role_maker(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "127.0.0.1:7164")
    monkeypatch.setenv("PADDLE_PSERVER_ID", "0")
    from paddle_tpu.parallel.fleet import _Fleet
    f = _Fleet()
    f.init(is_collective=False)
    assert f._role_maker.is_server()
    assert f._ps_runtime is not None
