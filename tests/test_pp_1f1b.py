"""1F1B / interleaved pipeline: schedule validity, bubble accounting, and
loss+grad parity vs a sequential reference (reference test pattern:
hybrid_parallel_pp_transformer.py loss parity; pipeline_parallel.py:117).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.parallel as dist
from paddle_tpu.parallel.pp_schedules import (build_schedule,
                                              bubble_fraction,
                                              gpipe_bubble_fraction)
from paddle_tpu.parallel.pp_1f1b import (build_1f1b_train_step,
                                         segment_counts)


# ----------------------------------------------------------- schedule


class TestSchedule:
    @pytest.mark.parametrize("S,M,v", [(2, 2, 1), (4, 8, 1), (4, 8, 2),
                                       (3, 5, 1), (4, 4, 2)])
    def test_valid(self, S, M, v):
        sc = build_schedule(S, M, v)
        VS = S * v
        # every op exactly once, on the right device
        seen_f, seen_b = set(), set()
        f_time, b_time = {}, {}
        for t in range(sc.T):
            for i in range(S):
                vs = sc.f_vs[t, i]
                if vs >= 0:
                    assert vs % S == i
                    key = (int(vs), int(sc.f_mb[t, i]))
                    assert key not in seen_f
                    seen_f.add(key)
                    f_time[key] = t
                vs = sc.b_vs[t, i]
                if vs >= 0:
                    assert vs % S == i
                    key = (int(vs), int(sc.b_mb[t, i]))
                    assert key not in seen_b
                    seen_b.add(key)
                    b_time[key] = t
        assert len(seen_f) == VS * M
        assert len(seen_b) == VS * M
        # dependencies: fwd(vs,m) after fwd(vs-1,m)+1; bwd(vs,m) after
        # bwd(vs+1,m)+1 (comm latency 1 tick); bwd after own fwd
        for (vs, m), t in f_time.items():
            if vs > 0:
                assert t >= f_time[(vs - 1, m)] + 1
        for (vs, m), t in b_time.items():
            if vs < VS - 1:
                assert t >= b_time[(vs + 1, m)] + 1
            assert t >= f_time[(vs, m)] + 1

    def test_1f1b_memory_bound(self):
        # in-flight (fwd done, bwd not) per device never exceeds v*(S-i)
        S, M = 4, 16
        sc = build_schedule(S, M, 1)
        inflight = [0] * S
        for t in range(sc.T):
            for i in range(S):
                if sc.f_vs[t, i] >= 0:
                    inflight[i] += 1
                if sc.b_vs[t, i] >= 0:
                    inflight[i] -= 1
                assert inflight[i] <= S - i
        # GPipe would hold M=16 in flight; 1F1B caps at S=4
        assert max(S - i for i in range(S)) < M

    def test_interleave_beats_gpipe_bubble(self):
        S, M = 4, 8
        gp = gpipe_bubble_fraction(S, M)
        one = bubble_fraction(build_schedule(S, M, 1))
        two = bubble_fraction(build_schedule(S, M, 2))
        # non-interleaved 1F1B: same-or-better bubble than GPipe;
        # interleaved must strictly beat it (the Megatron v-chunk effect)
        assert one <= gp + 1e-9
        assert two < gp - 1e-9
        assert two < one

    def test_segment_counts_param_weighted(self):
        counts, starts = segment_counts(6, 4, weights=[4, 1, 1, 1, 1, 4])
        assert counts.sum() == 6
        assert len(counts) == 4
        # heavy first block should sit alone-ish
        assert counts[0] <= 2


# ----------------------------------------------------------- numerics


def _make_params(L, V, H, seed=0):
    rng = np.random.RandomState(seed)
    blocks = [{"w": jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.3)}
              for _ in range(L)]
    embed = {"table": jnp.asarray(rng.randn(V, H).astype(np.float32) * 0.3)}
    head = {"wo": jnp.asarray(rng.randn(H, V).astype(np.float32) * 0.3)}
    return blocks, embed, head


def _block_fn(p, x):
    return jnp.tanh(x @ p["w"])


def _embed_fn(p, ids):
    return p["table"][ids]


def _head_loss_fn(p, hidden, labels):
    logits = hidden @ p["wo"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return -jnp.take_along_axis(logp, labels[..., None], -1).mean()


def _seq_loss(blocks, embed, head, ids, labels):
    x = _embed_fn(embed, ids)
    for bp in blocks:
        x = _block_fn(bp, x)
    return _head_loss_fn(head, x, labels)


def _unstack(d_blk, counts, starts, L):
    """[v, S, C, ...] grads -> per-block list matching original order."""
    v, S, C = d_blk["w"].shape[:3]
    out = [None] * L
    for vs in range(v * S):
        c, i = vs // S, vs % S
        for j in range(int(counts[vs])):
            out[int(starts[vs]) + j] = {"w": d_blk["w"][c, i, j]}
    return out


@pytest.mark.slow  # heaviest pp compiles (~20s)
@pytest.mark.parametrize("v,weights", [
    (1, None),                       # uniform 1F1B
    (1, [3, 1, 1, 1, 1, 3]),         # non-uniform (param-weighted)
    (2, None),                       # interleaved
])
def test_1f1b_parity_vs_sequential(v, weights):
    S, M = 4, 4
    L, V, H = 8 if v == 2 else 6, 32, 16
    B, sq = 8, 8
    if weights is not None and L != 6:
        weights = None
    mesh = dist.init_mesh(dp=2, pp=4)
    blocks, embed, head = _make_params(L, V, H)
    rng = np.random.RandomState(3)
    ids = jnp.asarray(rng.randint(0, V, size=(B, sq)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, V, size=(B, sq)).astype(np.int32))

    grad_fn, (stacked, emb_p, head_p, sched) = build_1f1b_train_step(
        _block_fn, _embed_fn, _head_loss_fn, blocks, embed, head,
        mesh, num_micro=M, interleave=v, block_weights=weights)
    loss, (d_blk, d_emb, d_head) = jax.jit(grad_fn)(
        stacked, emb_p, head_p, ids, labels)

    # sequential reference: mean over microbatches of per-mb mean loss
    def ref_loss(blocks, embed, head):
        mbs = ids.reshape(M, B // M, sq)
        lbs = labels.reshape(M, B // M, sq)
        tot = 0.0
        for m in range(M):
            tot = tot + _seq_loss(blocks, embed, head, mbs[m], lbs[m])
        return tot / M

    ref, ref_grads = jax.value_and_grad(
        lambda t: ref_loss(t["b"], t["e"], t["h"]))(
            {"b": blocks, "e": embed, "h": head})

    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(d_emb["table"]),
                               np.asarray(ref_grads["e"]["table"]),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(d_head["wo"]),
                               np.asarray(ref_grads["h"]["wo"]),
                               rtol=2e-4, atol=2e-5)
    counts, starts = segment_counts(L, S * v, weights)
    per_block = _unstack(
        {"w": np.asarray(d_blk["w"])}, counts, starts, L)
    for l in range(L):
        np.testing.assert_allclose(np.asarray(per_block[l]["w"]),
                                   np.asarray(ref_grads["b"][l]["w"]),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"block {l}")


# ------------------------------------------------------- forward-only pp

def test_pp_forward_eval_loss_parity():
    """Forward-only tick table (Engine.evaluate under pp — reference
    PipelineParallel.eval_batch): per-microbatch losses match the
    sequential model exactly."""
    from paddle_tpu.parallel.pp_1f1b import build_pp_forward_step
    mesh = dist.init_mesh(dp=2, pp=4)
    rng = np.random.RandomState(3)
    L, H, V = 8, 16, 32
    blocks = [{"w": jnp.asarray(rng.randn(H, H).astype(np.float32) * .3)}
              for _ in range(L)]
    embed = {"table": jnp.asarray(rng.randn(V, H).astype(np.float32) * .3)}
    head = {"wo": jnp.asarray(rng.randn(H, V).astype(np.float32) * .3)}

    def block_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def embed_fn(p, ids):
        return p["table"][ids]

    def head_loss_fn(p, hidden, labels):
        lg = (hidden @ p["wo"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, labels[..., None], -1).mean()

    ids = jnp.asarray(rng.randint(0, V, size=(8, 8)).astype(np.int32))
    fwd, (stk, ep, hp, _s) = build_pp_forward_step(
        block_fn, embed_fn, head_loss_fn, blocks, embed, head, mesh,
        num_micro=4)
    losses = jax.jit(fwd)(stk, ep, hp, ids, ids)

    def ref_loss(ids_mb):
        x = embed["table"][ids_mb]
        for bp in blocks:
            x = jnp.tanh(x @ bp["w"])
        lg = (x @ head["wo"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, ids_mb[..., None], -1).mean()

    refs = [float(ref_loss(ids[i * 2:(i + 1) * 2])) for i in range(4)]
    np.testing.assert_allclose(np.asarray(losses), refs, rtol=2e-5)


def test_pp_forward_predict_logits_parity():
    """head_out_fn path (Engine.predict under pp): stacked [M, mb, s, V]
    logits reassemble to the sequential model's full-batch logits."""
    from paddle_tpu.parallel.pp_1f1b import build_pp_forward_step
    mesh = dist.init_mesh(dp=2, pp=4)
    rng = np.random.RandomState(4)
    L, H, V = 8, 16, 32
    blocks = [{"w": jnp.asarray(rng.randn(H, H).astype(np.float32) * .3)}
              for _ in range(L)]
    embed = {"table": jnp.asarray(rng.randn(V, H).astype(np.float32) * .3)}
    head = {"wo": jnp.asarray(rng.randn(H, V).astype(np.float32) * .3)}

    def block_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def embed_fn(p, ids):
        return p["table"][ids]

    def head_out_fn(p, hidden, labels):
        return (hidden @ p["wo"]).astype(jnp.float32)

    ids = jnp.asarray(rng.randint(0, V, size=(8, 8)).astype(np.int32))
    fwd, (stk, ep, hp, _s) = build_pp_forward_step(
        block_fn, embed_fn, head_out_fn, blocks, embed, head, mesh,
        num_micro=4, out_batch_dims=(0, 1))
    lg = jax.jit(fwd)(stk, ep, hp, ids, ids)
    assert lg.shape == (4, 2, 8, V)

    def ref_logits(ids_mb):
        x = embed["table"][ids_mb]
        for bp in blocks:
            x = jnp.tanh(x @ bp["w"])
        return (x @ head["wo"]).astype(jnp.float32)

    want = jnp.stack([ref_logits(ids[i * 2:(i + 1) * 2])
                      for i in range(4)])
    np.testing.assert_allclose(np.asarray(lg), np.asarray(want),
                               rtol=2e-5, atol=1e-5)


def test_pp_forward_tied_interleaved():
    """Forward-only pass through tied-embedding + interleaved virtual
    stages: the same tie/gather layout as the train builder."""
    from paddle_tpu.parallel.pp_1f1b import (build_pp_forward_step,
                                             make_tied_lm_fns)
    mesh = dist.init_mesh(dp=2, pp=2)
    rng = np.random.RandomState(5)
    L, H, V = 8, 16, 32
    blocks = [{"w": jnp.asarray(rng.randn(H, H).astype(np.float32) * .3)}
              for _ in range(L)]
    embed = {"table": jnp.asarray(rng.randn(V, H).astype(np.float32) * .3)}

    def block_fn(p, x):
        return jnp.tanh(x @ p["w"])

    efn, hfn = make_tied_lm_fns()
    ids = jnp.asarray(rng.randint(0, V, size=(8, 8)).astype(np.int32))
    fwd, (stk, ep, hp, _s) = build_pp_forward_step(
        block_fn, efn, hfn, blocks, embed, {}, mesh, num_micro=4,
        interleave=2, tie_embed_head=True)
    losses = jax.jit(fwd)(stk, ep, hp, ids, ids)

    def ref_tied(ids_mb):
        x = embed["table"][ids_mb]
        for bp in blocks:
            x = jnp.tanh(x @ bp["w"])
        lg = (x @ embed["table"].T).astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.take_along_axis(logp, ids_mb[..., None], -1).mean()

    refs = [float(ref_tied(ids[i * 2:(i + 1) * 2])) for i in range(4)]
    np.testing.assert_allclose(np.asarray(losses), refs, rtol=2e-5)
