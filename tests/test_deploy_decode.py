"""export_decode/load_decode: the serialized prefill+decode archives must
reproduce model.generate() without any model code at load time."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.deploy_decode import export_decode, load_decode


class TestDeployDecode:
    def test_roundtrip_matches_generate(self, tmp_path):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(95)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(20)
        ids = rng.integers(0, 256, (2, 5)).astype(np.int32)
        prefix = str(tmp_path / "llama_gen")
        paths = export_decode(prefix, model, prompt_len=5,
                              max_new_tokens=6, batch=2)
        assert all(str(tmp_path) in p for p in paths)

        want = model.generate(pt.to_tensor(ids), max_new_tokens=6,
                              max_cache_len=11).numpy()
        gen = load_decode(prefix)
        got = gen.generate(ids)
        np.testing.assert_array_equal(got, want)

    def test_shape_contract_enforced(self, tmp_path):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
        pt.seed(96)
        model = GPTForCausalLM(gpt2_tiny())
        model.eval()
        prefix = str(tmp_path / "gpt_gen")
        export_decode(prefix, model, prompt_len=4, max_new_tokens=3,
                      batch=1)
        gen = load_decode(prefix)
        out = gen.generate(np.zeros((1, 4), np.int32))
        assert out.shape == (1, 7)
        with pytest.raises(ValueError, match="archive serves shape"):
            gen.generate(np.zeros((2, 4), np.int32))
        with pytest.raises(ValueError, match="archive serves shape"):
            gen.generate(np.zeros((1, 6), np.int32))
