"""Engine strategy wiring (VERDICT r3 #4): amp / gradient_merge /
pipeline flags must change the built step; unimplementable config raises.

Reference: auto_parallel/parallelizer_v2.py:48 (_apply_pre/_apply_post
passes driven by Strategy), strategy.py.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.auto_parallel import Engine, Strategy


def _engine(strategy, n_feat=4):
    net = nn.Sequential(nn.Linear(n_feat, 16), nn.ReLU(),
                        nn.Linear(16, 1))
    return Engine(model=net, loss=nn.MSELoss(),
                  optimizer=pt.optimizer.Adam(
                      learning_rate=0.01, parameters=net.parameters()),
                  strategy=strategy), net


def _batch(rng, n=8, n_feat=4):
    x = rng.standard_normal((n, n_feat)).astype("float32")
    y = rng.standard_normal((n, 1)).astype("float32")
    return {"inputs": (x,), "labels": (y,)}


def test_amp_bf16_changes_param_dtype():
    dist.init_mesh(dp=8)
    strat = Strategy()
    strat.amp.enable = True
    strat.amp.dtype = "bfloat16"
    eng, _net = _engine(strat)
    eng._prepare()
    dtypes = {str(v.dtype) for v in eng._params.values()}
    assert dtypes == {"bfloat16"}, dtypes
    rng = np.random.default_rng(0)
    loss, eng._params, eng._opt_state = eng._step_fn(
        eng._params, eng._opt_state, _batch(rng), 1, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_amp_fp16_loss_scaling_returns_unscaled_loss():
    dist.init_mesh(dp=8)
    rng = np.random.default_rng(1)
    batch = _batch(rng)

    strat = Strategy()
    pt.seed(42)
    eng0, _ = _engine(strat)
    eng0._prepare()
    l0, *_ = eng0._step_fn(eng0._params, eng0._opt_state, batch, 1,
                           jax.random.PRNGKey(0))

    strat16 = Strategy()
    strat16.amp.enable = True
    strat16.amp.dtype = "float16"
    pt.seed(42)
    eng1, _ = _engine(strat16)
    eng1._prepare()
    l1, *_ = eng1._step_fn(eng1._params, eng1._opt_state, batch, 1,
                           jax.random.PRNGKey(0))
    # loss reported UNSCALED despite the 2^15 backward scale
    assert abs(float(l1) - float(l0)) < 0.1 * max(1.0, abs(float(l0)))


def test_amp_unknown_dtype_raises():
    strat = Strategy()
    strat.amp.enable = True
    strat.amp.dtype = "float8"
    eng, _ = _engine(strat)
    with pytest.raises(NotImplementedError):
        eng._prepare()


def test_gradient_merge_updates_every_kth_step():
    dist.init_mesh(dp=8)
    strat = Strategy()
    strat.gradient_merge.enable = True
    strat.gradient_merge.k_steps = 2
    eng, _ = _engine(strat)
    eng._prepare()
    assert "_accum" in eng._opt_state, "gradient merge must add accum state"
    rng = np.random.default_rng(2)
    p0 = {k: np.asarray(v) for k, v in eng._params.items()}
    # step 1 of 2: accumulate only, params unchanged
    _l, p1, s1 = eng._step_fn(eng._params, eng._opt_state, _batch(rng), 1,
                              jax.random.PRNGKey(0))
    for k in p0:
        np.testing.assert_array_equal(p0[k], np.asarray(p1[k]))
    acc_norm = sum(float(jnp.abs(a).sum())
                   for a in jax.tree_util.tree_leaves(s1["_accum"]))
    assert acc_norm > 0, "grads did not accumulate"
    # step 2 of 2: apply
    _l, p2, s2 = eng._step_fn(p1, s1, _batch(rng), 2, jax.random.PRNGKey(0))
    changed = any(not np.array_equal(p0[k], np.asarray(p2[k])) for k in p0)
    assert changed, "k-th step must apply the merged update"
    acc_norm2 = sum(float(jnp.abs(a).sum())
                    for a in jax.tree_util.tree_leaves(s2["_accum"]))
    assert acc_norm2 == 0, "accumulators must reset after the update"


@pytest.mark.slow  # compile-heavy pipeline e2e
def test_pipeline_routes_to_1f1b():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    dist.init_mesh(dp=4, pp=2)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    strat = Strategy()
    strat.pipeline.enable = True
    strat.pipeline.accumulate_steps = 2
    eng = Engine(model=model, loss=model.loss,
                 optimizer=pt.optimizer.AdamW(
                     learning_rate=1e-4, parameters=model.parameters()),
                 strategy=strat)
    eng._prepare()
    assert getattr(eng, "_pp_mode", False)
    assert "blocks" in eng._params, "pipeline params must be stage-stacked"
    rng = np.random.default_rng(3)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int32")
    loss, eng._params, eng._opt_state = eng._step_fn(
        eng._params, eng._opt_state,
        {"inputs": (ids,), "labels": (ids,)}, 1, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    # trained stage-stacked params write back into the eager module
    before = model.model.layers[0].raw_params()
    w_name = next(iter(before))
    before_w = np.asarray(before[w_name]).copy()
    model.pipeline_recompose(eng._params, eng._pp_layout)
    after_w = np.asarray(model.model.layers[0].raw_params()[w_name])
    assert not np.array_equal(before_w, after_w), \
        "recompose must write trained weights back"


@pytest.mark.slow  # compile-heavy pipeline e2e
def test_pipeline_fp16_loss_scaling():
    """fp16 amp THROUGH the pipeline builder (closes the r4 refusal —
    reference engine.py fp16 pass composes with pipeline): the head
    loss is scaled inside the tick table, grads unscale pre-update,
    and the reported loss is unscaled."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    dist.init_mesh(dp=4, pp=2)
    cfg = llama_tiny()
    pt.seed(5)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int32")
    ref = float(model.loss(model(pt.to_tensor(ids)),
                           pt.to_tensor(ids)).numpy())
    strat = Strategy()
    strat.pipeline.enable = True
    strat.pipeline.accumulate_steps = 2
    strat.amp.enable = True
    strat.amp.dtype = "float16"
    eng = Engine(model=model, loss=model.loss,
                 optimizer=pt.optimizer.AdamW(
                     learning_rate=1e-4, parameters=model.parameters()),
                 strategy=strat)
    eng._prepare()
    dtypes = {str(a.dtype)
              for a in jax.tree_util.tree_leaves(eng._params)}
    assert dtypes == {"float16"}, dtypes
    p_before = [np.asarray(a).copy()
                for a in jax.tree_util.tree_leaves(eng._params)]
    p, s = eng._params, eng._opt_state
    batch = {"inputs": (ids,), "labels": (ids,)}
    losses = []
    for i in range(1, 7):
        loss, p, s = eng._step_fn(p, s, batch, i, jax.random.PRNGKey(0))
        losses.append(float(loss))
    # unscaled despite the backward scale; fp16 model ~ fp32 ref
    assert abs(losses[0] - ref) < 0.05 * max(1.0, abs(ref))
    assert all(np.isfinite(losses)), losses
    # the DYNAMIC scaler may skip early overflowing steps (halving the
    # scale); within a few steps it must settle and actually update
    changed = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(p_before, jax.tree_util.tree_leaves(p)))
    assert changed, "scaled grads must still produce an update"
    assert float(s["_scale"]) >= 1.0
    assert losses[-1] <= losses[0] + 1e-3, losses


@pytest.mark.slow  # compile-heavy pipeline e2e
def test_pipeline_gradient_merge():
    """gradient_merge k_steps>1 composes WITH the pipeline (closes the
    r4 refusal): step 1 only accumulates, step k applies and resets."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    dist.init_mesh(dp=4, pp=2)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    strat = Strategy()
    strat.pipeline.enable = True
    strat.pipeline.accumulate_steps = 2
    strat.gradient_merge.enable = True
    strat.gradient_merge.k_steps = 2
    eng = Engine(model=model, loss=model.loss,
                 optimizer=pt.optimizer.AdamW(
                     learning_rate=1e-4, parameters=model.parameters()),
                 strategy=strat)
    eng._prepare()
    assert "_accum" in eng._opt_state
    rng = np.random.default_rng(6)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int32")
    batch = {"inputs": (ids,), "labels": (ids,)}
    p0 = [np.asarray(a).copy()
          for a in jax.tree_util.tree_leaves(eng._params)]
    _l, p1, s1 = eng._step_fn(eng._params, eng._opt_state, batch, 1,
                              jax.random.PRNGKey(0))
    for a, b in zip(p0, jax.tree_util.tree_leaves(p1)):
        np.testing.assert_array_equal(a, np.asarray(b))
    acc = sum(float(jnp.abs(a).sum())
              for a in jax.tree_util.tree_leaves(s1["_accum"]))
    assert acc > 0
    _l, p2, s2 = eng._step_fn(p1, s1, batch, 2, jax.random.PRNGKey(0))
    assert any(not np.array_equal(a, np.asarray(b))
               for a, b in zip(p0, jax.tree_util.tree_leaves(p2)))
    acc2 = sum(float(jnp.abs(a).sum())
               for a in jax.tree_util.tree_leaves(s2["_accum"]))
    assert acc2 == 0


@pytest.mark.slow  # compile-heavy pipeline e2e
def test_pipeline_evaluate_and_predict():
    """evaluate()/predict() under strategy.pipeline run the forward-only
    tick table over the train step's stage-stacked params (closes the
    r4 refusals; reference engine.py:1328 evaluate/predict under every
    strategy)."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    dist.init_mesh(dp=4, pp=2)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    strat = Strategy()
    strat.pipeline.enable = True
    strat.pipeline.accumulate_steps = 2
    eng = Engine(model=model, loss=model.loss,
                 optimizer=pt.optimizer.AdamW(
                     learning_rate=1e-4, parameters=model.parameters()),
                 strategy=strat)
    eng._prepare()
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int32")
    ref_loss = float(model.loss(model(pt.to_tensor(ids)),
                                pt.to_tensor(ids)).numpy())
    ref_logits = np.asarray(model(pt.to_tensor(ids)).numpy())

    out = eng.evaluate([{"inputs": (ids,), "labels": (ids,)}])
    np.testing.assert_allclose(out["eval_loss"], ref_loss, rtol=2e-4)

    preds = eng.predict([{"inputs": (ids,)}])
    assert len(preds) == 1 and preds[0].shape == ref_logits.shape
    np.testing.assert_allclose(preds[0], ref_logits, rtol=2e-3,
                               atol=2e-4)


def test_unknown_fused_pass_raises():
    strat = Strategy()
    strat.fused_passes.enable = True
    strat.fused_passes.fused_passes_list = ["fused_quantum_annealing"]
    eng, _ = _engine(strat)
    with pytest.raises(NotImplementedError):
        eng._prepare()


def test_dataset_shards_raises():
    strat = Strategy()
    strat.dataset.num_shards = 4
    eng, _ = _engine(strat)
    with pytest.raises(NotImplementedError):
        eng._prepare()


@pytest.mark.slow  # compile-heavy pipeline e2e
def test_gpt_tied_pipeline_matches_eager():
    """GPT through the Engine pipeline keeps its WEIGHT TYING (the
    reference SharedLayerDesc GPT demo): the builder stores the shared
    table pp-sharded and the pipeline loss matches the eager model."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_tiny
    dist.init_mesh(dp=4, pp=2)
    cfg = gpt2_tiny(dropout=0.0)
    model = GPTForCausalLM(cfg)
    strat = Strategy()
    strat.pipeline.enable = True
    strat.pipeline.accumulate_steps = 2
    eng = Engine(model=model, loss=model.loss,
                 optimizer=pt.optimizer.AdamW(
                     learning_rate=1e-4, parameters=model.parameters()),
                 strategy=strat)
    eng._prepare()
    assert eng._params["head"].keys() == {"ln_g", "ln_b"}, \
        "tied pipeline must carry no separate lm head weight"
    assert "pp" in str(eng._shardings[0]["embed"]["table"].spec)
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype("int32")
    # eager reference BEFORE training (same weights)
    ref = float(model.loss(model(pt.to_tensor(ids)),
                           pt.to_tensor(ids)).numpy())
    loss, new_p, new_s = eng._step_fn(
        eng._params, eng._opt_state,
        {"inputs": (ids,), "labels": (ids,)}, 1, jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(loss), ref, rtol=2e-4)
    # write-back keeps the tie: lm_head_weight IS wte.weight
    eng._params = new_p
    model.pipeline_recompose(eng._params, eng._pp_layout)
    assert model.lm_head_weight is model.gpt.wte.weight
