"""Beam search on the KV-cache decode (decode_loop.beam_generate):
num_beams=1 equals greedy; a beam wide enough to be exhaustive finds the
global maximum-likelihood sequence; eos freezes beams."""
import numpy as np
import pytest

import paddle_tpu as pt


def _tiny_vocab_model(V=6):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(97)
    cfg = LlamaConfig(vocab_size=V, hidden_size=32, num_layers=1,
                      num_heads=2, num_kv_heads=2, intermediate_size=64,
                      max_seq_len=32)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


class TestBeamSearch:
    def test_single_beam_equals_greedy(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(98)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        ids = np.arange(8, dtype=np.int32).reshape(2, 4)
        greedy = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                                max_cache_len=32)
        beam1 = model.generate(pt.to_tensor(ids), max_new_tokens=5,
                               max_cache_len=32, num_beams=1)
        np.testing.assert_array_equal(beam1.numpy(), greedy.numpy())

    @pytest.mark.slow
    def test_exhaustive_beam_finds_global_optimum(self):
        """V=6, 3 new tokens, num_beams=36 >= V^2: the beam holds every
        depth-2 prefix, so it must return the argmax over all 216
        completions scored by full-forward log-likelihood. (slow: 216
        full forwards; the cheaper beam contracts stay tier-1.)"""
        model = _tiny_vocab_model(V=6)
        V, NEW = 6, 3
        rng = np.random.default_rng(22)
        prompt = rng.integers(0, V, (1, 3)).astype(np.int32)

        def seq_logprob(completion):
            ids = np.concatenate([prompt[0], completion])[None]
            logits = model(pt.to_tensor(ids.astype(np.int32))).numpy()[0]
            logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
            total = 0.0
            for j, tok in enumerate(completion):
                total += logp[prompt.shape[1] - 1 + j, tok]
            return total

        best_score, best_seq = -np.inf, None
        for a in range(V):
            for b in range(V):
                for c in range(V):
                    sc = seq_logprob(np.array([a, b, c]))
                    if sc > best_score:
                        best_score, best_seq = sc, (a, b, c)

        out = model.generate(pt.to_tensor(prompt), max_new_tokens=NEW,
                             max_cache_len=16, num_beams=36).numpy()
        assert tuple(out[0, 3:]) == best_seq, (
            f"beam {tuple(out[0, 3:])} != brute-force {best_seq} "
            f"(score {best_score:.4f})")

    @pytest.mark.slow
    def test_beam_improves_or_matches_greedy_likelihood(self):
        model = _tiny_vocab_model(V=16)
        rng = np.random.default_rng(23)
        prompt = rng.integers(0, 16, (1, 4)).astype(np.int32)

        def ll(full):
            logits = model(pt.to_tensor(full[None])).numpy()[0]
            logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
            return sum(logp[3 + j, tok]
                       for j, tok in enumerate(full[4:]))

        greedy = model.generate(pt.to_tensor(prompt), max_new_tokens=4,
                                max_cache_len=16).numpy()[0]
        beam = model.generate(pt.to_tensor(prompt), max_new_tokens=4,
                              max_cache_len=16, num_beams=8).numpy()[0]
        assert ll(beam) >= ll(greedy) - 1e-5

    def test_eos_freezes_beams(self):
        model = _tiny_vocab_model(V=6)
        prompt = np.zeros((1, 2), np.int32)
        greedy = model.generate(pt.to_tensor(prompt), max_new_tokens=6,
                                max_cache_len=16).numpy()[0, 2:]
        eos = int(greedy[1])
        out = model.generate(pt.to_tensor(prompt), max_new_tokens=6,
                             max_cache_len=16, num_beams=4,
                             eos_token_id=eos).numpy()[0, 2:]
        hit = np.where(out == eos)[0]
        assert len(hit) and (out[hit[0]:] == eos).all()

    def test_beam_reuse_across_prompt_lengths(self):
        """code-review r5: the compiled beam program must take t0 at
        runtime — a second call with a DIFFERENT prompt length must not
        reuse a stale baked offset."""
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        pt.seed(99)
        model = LlamaForCausalLM(llama_tiny())
        model.eval()
        rng = np.random.default_rng(24)
        ids4 = rng.integers(0, 256, (1, 4)).astype(np.int32)
        ids6 = rng.integers(0, 256, (1, 6)).astype(np.int32)
        model.generate(pt.to_tensor(ids4), max_new_tokens=3,
                       max_cache_len=32, num_beams=2)   # warm t0=4
        got = model.generate(pt.to_tensor(ids6), max_new_tokens=3,
                             max_cache_len=32, num_beams=2).numpy()
        pt.seed(99)
        fresh = LlamaForCausalLM(llama_tiny())
        fresh.eval()
        want = fresh.generate(pt.to_tensor(ids6), max_new_tokens=3,
                              max_cache_len=32, num_beams=2).numpy()
        np.testing.assert_array_equal(got, want)

    def test_beams_exclusive_with_sampling(self):
        model = _tiny_vocab_model()
        with pytest.raises(ValueError, match="mutually exclusive"):
            model.generate(pt.to_tensor(np.zeros((1, 2), np.int32)),
                           max_new_tokens=2, num_beams=2, do_sample=True)
