"""Pallas fused GEMM epilogue vs oracle (interpret mode on CPU) +
public incubate API grads (reference fused_gemm_epilogue_op.cu tests)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import gemm_epilogue as ge


def _data(m=256, k=512, n=256):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(k, n).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(n).astype(np.float32))
    return x, w, b


@pytest.mark.parametrize("act", ["none", "relu", "gelu"])
def test_kernel_matches_oracle(act):
    x, w, b = _data()
    out = ge._gemm_epilogue_pallas(x, w, b, act, interpret=True)
    ref = ge._ref(x, w, b, act)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_no_bias():
    x, w, _ = _data()
    out = ge._gemm_epilogue_pallas(x, w, None, "relu", interpret=True)
    ref = ge._ref(x, w, None, "relu")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("act", ["none", "relu", "gelu"])
def test_custom_vjp_grads(act):
    x, w, b = _data(64, 32, 48)  # CPU fallback path; vjp must be exact

    def loss(x, w, b):
        return (ge.fused_gemm_epilogue(x, w, b, act) ** 2).sum()

    def ref_loss(x, w, b):
        return (ge._ref(x, w, b, act) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
    r = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(g, r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-4, atol=2e-4)


def test_public_api_batched_and_grad():
    import paddle_tpu as pt
    from paddle_tpu.incubate.nn.functional import fused_linear_activation
    rng = np.random.RandomState(1)
    x = pt.to_tensor(rng.randn(4, 8, 16).astype(np.float32),
                     stop_gradient=False)
    y = pt.to_tensor(rng.randn(16, 24).astype(np.float32),
                     stop_gradient=False)
    b = pt.to_tensor(rng.randn(24).astype(np.float32),
                     stop_gradient=False)
    out = fused_linear_activation(x, y, b, activation="relu")
    assert tuple(out.numpy().shape) == (4, 8, 24)
    out.sum().backward()
    assert x.grad is not None and y.grad is not None and b.grad is not None
    # grads beyond the relu zero-region must be exactly the matmul chain
    ref = np.maximum(x.numpy() @ y.numpy() + b.numpy(), 0)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)
