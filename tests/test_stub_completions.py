"""Tests for previously-stubbed capabilities: forward_grad (static
forward-mode AD), SpectralNorm, grouped conv_transpose, and
convert_to_mixed_precision."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt


def test_forward_grad_static():
    import paddle_tpu.static as static
    from paddle_tpu.incubate.autograd import forward_grad

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", shape=[3], dtype="float32")
        y = x * x + x
        (jv,) = forward_grad([y], [x])

    exe = static.Executor()
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[jv])
    # d(x^2+x)/dx with tangent 1 = 2x+1
    np.testing.assert_allclose(out[0], 2 * xv + 1, rtol=1e-6)


def test_forward_grad_custom_tangent():
    import paddle_tpu.static as static
    from paddle_tpu.incubate.autograd import forward_grad

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", shape=[2], dtype="float32")
        y = pt.sin(x)
        jv = forward_grad(y, x, grad_inputs=np.array([2.0, 0.5],
                                                     np.float32))

    exe = static.Executor()
    xv = np.array([0.3, 1.1], np.float32)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[jv])
    np.testing.assert_allclose(out[0], np.cos(xv) * [2.0, 0.5],
                               rtol=1e-6)


def test_forward_grad_dynamic_batch():
    # review regression: tangents must materialize at RUN time so a
    # dynamic (-1) feed dim works
    import paddle_tpu.static as static
    from paddle_tpu.incubate.autograd import forward_grad

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", shape=[-1, 3], dtype="float32")
        y = x * x
        (jv,) = forward_grad([y], [x])

    exe = static.Executor()
    xv = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[jv])
    np.testing.assert_allclose(out[0], 2 * xv, rtol=1e-6)


def test_spectral_norm_unit_sigma():
    sn = pt.nn.SpectralNorm([4, 6], dim=0, power_iters=20)
    rng = np.random.RandomState(0)
    w = pt.to_tensor(rng.randn(4, 6).astype(np.float32))
    out = sn(w)
    # normalized weight must have top singular value ~1
    sig = np.linalg.svd(out.numpy(), compute_uv=False)[0]
    np.testing.assert_allclose(sig, 1.0, rtol=1e-3)


def test_spectral_norm_grad_flows():
    sn = pt.nn.SpectralNorm([3, 3], power_iters=5)
    w = pt.to_tensor(np.eye(3, dtype=np.float32) * 2, stop_gradient=False)
    out = sn(w)
    out.sum().backward()
    assert w.grad is not None
    assert np.isfinite(w.grad.numpy()).all()


def test_grouped_conv2d_transpose():
    rng = np.random.RandomState(0)
    g, cin, cout_pg = 2, 4, 3
    x = rng.randn(1, cin, 5, 5).astype(np.float32)
    w = rng.randn(cin, cout_pg, 3, 3).astype(np.float32)
    out = pt.conv2d_transpose(pt.to_tensor(x), pt.to_tensor(w),
                              stride=1, padding=0, groups=g)
    assert list(out.shape) == [1, g * cout_pg, 7, 7]
    # group 0 must equal the ungrouped transpose on its channel slice
    ref0 = pt.conv2d_transpose(
        pt.to_tensor(x[:, :cin // g]), pt.to_tensor(w[:cin // g]),
        stride=1, padding=0, groups=1)
    np.testing.assert_allclose(out.numpy()[:, :cout_pg],
                               ref0.numpy(), rtol=1e-4, atol=1e-5)


def test_convert_to_mixed_precision(tmp_path):
    import paddle_tpu.inference as infer
    from paddle_tpu.static import InputSpec

    net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                           pt.nn.Linear(8, 2))
    src = str(tmp_path / "m_fp32")
    pt.jit.save(net, src, input_spec=[InputSpec([2, 4], "float32", "x")])

    # full conversion (model available): params cast to bf16
    dst = str(tmp_path / "m_bf16")
    infer.convert_to_mixed_precision(src, dst, "bf16", model=net)
    cfg = infer.Config(dst)
    pred = infer.create_predictor(cfg)
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    ref = net(pt.to_tensor(x)).numpy()
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(x)
    pred.run()
    got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(np.asarray(got, np.float32), ref,
                               rtol=5e-2, atol=5e-2)

    # archive-only conversion: boundary-cast wrapper, still runs
    dst2 = str(tmp_path / "m_wrap")
    infer.convert_to_mixed_precision(src, dst2, "bf16")
    pred2 = infer.create_predictor(infer.Config(dst2))
    h2 = pred2.get_input_handle(pred2.get_input_names()[0])
    h2.copy_from_cpu(x.astype(np.float32))
    pred2.run()
    out2 = pred2.get_output_handle(
        pred2.get_output_names()[0]).copy_to_cpu()
    assert "bfloat16" in str(np.asarray(out2).dtype) or np.allclose(
        np.asarray(out2, np.float32), ref, rtol=5e-2, atol=5e-2)
