"""Module-level replica factories for SPAWNED host processes.

``remote.spawn_replica_host(factory)`` pickles the factory by
reference, so it must live in an importable module (not a test body).
A spawned child re-imports this module from scratch — force the CPU
platform BEFORE anything touches jax, exactly as conftest.py does for
the parent (the child does not run conftest)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from _serving_stub import StubModel  # noqa: E402
from paddle_tpu.inference.continuous_batching import \
    ContinuousBatchingServer  # noqa: E402


def make_stub_server(**kw):
    """A paged StubModel server with the router-test defaults; any
    kwarg overrides pass straight through (``do_sample=True`` for the
    seeded-sampling parity drills)."""
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_cache_len", 64)
    kw.setdefault("cache_backend", "paged")
    kw.setdefault("page_size", 8)
    return ContinuousBatchingServer(StubModel(), **kw)


def make_slow_stub_server(tick_sleep_s=0.01, **kw):
    """A stub server whose serve tick is paced by ``tick_sleep_s``:
    spawned kill-drills need the decode loop slow enough that a
    migration call arriving over the wire reliably catches requests
    MID-decode (an unpaced StubModel drains a 48-token budget inside
    one client round-trip)."""
    import time

    srv = make_stub_server(**kw)
    inner = srv._fire_callbacks

    def paced():
        time.sleep(tick_sleep_s)
        inner()

    srv._fire_callbacks = paced
    return srv
