"""BERT family (reference dygraph_to_static/test_bert.py pattern:
construct, forward shapes, pretraining loss decreases, jit parity)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.bert import (BertForPretraining,
                                    BertForSequenceClassification,
                                    BertModel, bert_tiny)

RNG = np.random.RandomState(0)


def _ids(b=2, s=16, vocab=128):
    return pt.to_tensor(RNG.randint(0, vocab, size=(b, s)).astype(
        np.int64))


def test_bert_model_shapes():
    cfg = bert_tiny()
    m = BertModel(cfg)
    m.eval()
    seq, pooled = m(_ids())
    assert list(seq.shape) == [2, 16, 64]
    assert list(pooled.shape) == [2, 64]


@pytest.mark.slow
def test_pretraining_loss_decreases():
    # slow: eager pretraining steps; forward-shape and jit-parity
    # contracts stay tier-1, and gpt/llama tiny training descent runs
    # tier-1 in test_models
    pt.seed(0)
    cfg = bert_tiny()
    model = BertForPretraining(cfg)
    model.eval()  # dropout 0 anyway; deterministic
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    ids = _ids(4, 16)
    labels = _ids(4, 16)
    nsp_labels = pt.to_tensor(RNG.randint(0, 2, size=(4,)).astype(
        np.int64))
    first = None
    for _ in range(8):
        mlm, nsp = model(ids)
        loss = model.loss(mlm, nsp, labels, nsp_labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    assert float(loss.numpy()) < first


def test_sequence_classification_and_jit():
    import jax
    cfg = bert_tiny()
    m = BertForSequenceClassification(cfg, num_classes=3)
    m.eval()
    ids = _ids(2, 16)
    eager = m(ids).numpy()
    assert eager.shape == (2, 3)

    from paddle_tpu.jit import functional_call
    params = m.raw_params()
    buffers = {n: b._value for n, b in m.named_buffers()}

    def fwd(p, i):
        return functional_call(m, p, i, buffers=buffers or None)

    jitted = jax.jit(fwd)(params, ids._value)
    np.testing.assert_allclose(np.asarray(jitted), eager, rtol=2e-5,
                               atol=2e-5)


def test_masked_labels_ignore_index():
    cfg = bert_tiny()
    model = BertForPretraining(cfg)
    model.eval()
    ids = _ids(2, 8)
    mlm, nsp = model(ids)
    labels = np.full((2, 8), -100, np.int64)
    labels[0, 3] = 7  # single supervised position
    loss = model.loss(mlm, nsp, pt.to_tensor(labels),
                      pt.to_tensor(np.array([0, 1], np.int64)))
    assert np.isfinite(float(loss.numpy()))


def test_attention_mask_masks_padding():
    cfg = bert_tiny()
    m = BertModel(cfg)
    m.eval()
    ids = _ids(2, 8)
    mask = np.ones((2, 8), np.int64)
    mask[:, 6:] = 0              # last two tokens are padding
    full, _ = m(ids)
    masked, _ = m(ids, attention_mask=pt.to_tensor(mask))
    # non-padding positions must differ from the unmasked run (padding
    # was attended before), and outputs stay finite
    assert np.isfinite(masked.numpy()).all()
    assert not np.allclose(masked.numpy()[:, :6], full.numpy()[:, :6])
