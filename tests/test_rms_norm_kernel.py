"""Pallas rms_norm backward kernel vs oracle (interpret mode)."""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import rms_norm as rn


def test_bwd_kernel_matches_oracle():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(512, 256).astype(np.float32))
    w = jnp.asarray(rng.randn(256).astype(np.float32))
    g = jnp.asarray(rng.randn(512, 256).astype(np.float32))
    dx, dw = rn._pallas_bwd(x, w, g, 1e-6, interpret=True)
    rdx, rdw = rn._ref_bwd(x, w, g, 1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rdx),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(rdw),
                               rtol=2e-4, atol=2e-4)


def test_bwd_kernel_3d_and_vjp_consistency():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 64, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(128).astype(np.float32))
    g = jnp.asarray(rng.randn(4, 64, 128).astype(np.float32))
    dx, dw = rn._pallas_bwd(x, w, g, 1e-6, interpret=True)

    _, vjp = jax.vjp(lambda a, b: rn._ref_fwd(a, b, 1e-6), x, w)
    rdx, rdw = vjp(g)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rdx),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(rdw),
                               rtol=2e-4, atol=2e-4)
