"""LoRA (paddle_tpu.peft): zero-init delta, adapter-only training,
merge/unmerge round trip, checkpoint surface."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.peft import (LoRALinear, apply_lora, load_lora_state_dict,
                             lora_parameters, lora_state_dict, merge_lora)


def _llama():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(91)
    return LlamaForCausalLM(llama_tiny())


class TestLoRA:
    def test_wrap_is_identity_until_trained(self):
        model = _llama()
        model.eval()
        ids = np.arange(8, dtype=np.int32).reshape(2, 4)
        before = model(pt.to_tensor(ids)).numpy()
        apply_lora(model, rank=4)
        after = model(pt.to_tensor(ids)).numpy()
        np.testing.assert_allclose(after, before, rtol=1e-6)

    def test_train_updates_only_adapters(self):
        model = _llama()
        apply_lora(model, rank=4, targets=("q_proj", "v_proj"))
        params = lora_parameters(model)
        assert len(params) == 2 * 2 * model.cfg.num_layers  # A,B per proj
        base_w = model.model.layers[0].self_attn.q_proj.base.weight
        base_before = base_w.numpy().copy()

        opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=params)
        ids = pt.to_tensor(np.arange(8, dtype=np.int32).reshape(2, 4))
        for _ in range(2):
            logits = model(ids)
            loss = model.loss(logits, ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
        lora_B = model.model.layers[0].self_attn.q_proj.lora_B.numpy()
        assert np.abs(lora_B).max() > 0, "adapter B never moved"
        np.testing.assert_array_equal(base_w.numpy(), base_before)
        # frozen non-target layers hold too
        assert model.model.layers[0].mlp.gate_proj.weight.stop_gradient

    def test_merge_matches_adapter_forward(self):
        model = _llama()
        model.eval()
        apply_lora(model, rank=4)
        # push the adapters off zero deterministically
        for _, sub in model.named_sublayers():
            if isinstance(sub, LoRALinear):
                sub.lora_B._replace_value(
                    np.full(sub.lora_B.shape, 0.01, "float32"))
        ids = np.arange(6, dtype=np.int32).reshape(1, 6)
        want = model(pt.to_tensor(ids)).numpy()
        merge_lora(model)
        got = model(pt.to_tensor(ids)).numpy()
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)
        # unmerge restores the un-adapted weight path
        for _, sub in model.named_sublayers():
            if isinstance(sub, LoRALinear):
                sub.unmerge()
        again = model(pt.to_tensor(ids)).numpy()
        np.testing.assert_allclose(again, want, rtol=2e-5, atol=1e-5)

    def test_unwrap_restores_structure_for_generate(self):
        """After unwrap_lora the decode builders (which read the original
        raw-param names) work, and greedy tokens reflect the adapters."""
        from paddle_tpu.peft import unwrap_lora
        model = _llama()
        model.eval()
        ids = np.arange(4, dtype=np.int32)[None]
        base_out = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                                  max_cache_len=32).numpy()
        apply_lora(model, rank=4)
        for _, sub in model.named_sublayers():
            if isinstance(sub, LoRALinear):
                sub.lora_B._replace_value(
                    np.full(sub.lora_B.shape, 0.05, "float32"))
        want_logits = model(pt.to_tensor(ids)).numpy()
        unwrap_lora(model)
        model.reset_generate_cache()
        np.testing.assert_allclose(model(pt.to_tensor(ids)).numpy(),
                                   want_logits, rtol=2e-5, atol=1e-5)
        out = model.generate(pt.to_tensor(ids), max_new_tokens=4,
                             max_cache_len=32).numpy()
        assert out.shape == base_out.shape
        assert not np.array_equal(out, base_out), \
            "adapters had no effect after unwrap (delta lost?)"

    def test_state_dict_roundtrip_and_guards(self):
        model = _llama()
        apply_lora(model, rank=2)
        sd = lora_state_dict(model)
        assert all(".lora_" in k for k in sd)
        m2 = _llama()
        apply_lora(m2, rank=2)
        load_lora_state_dict(m2, sd)
        for k, v in lora_state_dict(m2).items():
            np.testing.assert_array_equal(v, sd[k])
        with pytest.raises(ValueError, match="no Linear sublayers"):
            apply_lora(_llama(), targets=("nonexistent",))
        with pytest.raises(ValueError, match="no LoRA layers"):
            lora_parameters(_llama())
