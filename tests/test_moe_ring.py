"""MoE layer + ring/Ulysses attention tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.parallel as dist
from paddle_tpu.parallel.mesh import P


def test_moe_forward_and_grads():
    from paddle_tpu.parallel.moe import MoELayer
    layer = MoELayer(d_model=16, num_experts=4, d_hidden=32, gate="gshard",
                     capacity_factor=2.0)
    x = pt.to_tensor(np.random.randn(2, 8, 16).astype(np.float32),
                     stop_gradient=False)
    out = layer(x)
    assert out.shape == [2, 8, 16]
    assert layer.aux_loss is not None
    (out.sum() + layer.aux_loss * 0.01).backward()
    assert layer.experts.w1.grad is not None
    assert layer.gate.gate.weight.grad is not None


def test_moe_switch_gate():
    from paddle_tpu.parallel.moe import MoELayer
    layer = MoELayer(d_model=8, num_experts=2, d_hidden=16, gate="switch",
                     capacity_factor=4.0)
    x = pt.to_tensor(np.random.randn(1, 16, 8).astype(np.float32))
    out = layer(x)
    assert out.shape == [1, 16, 8]


def test_moe_capacity_sane():
    """With generous capacity, top-2 MoE output ~= dense mixture of experts."""
    from paddle_tpu.parallel.moe import MoELayer
    layer = MoELayer(d_model=8, num_experts=2, d_hidden=8, gate="gshard",
                     capacity_factor=8.0)
    x = pt.to_tensor(np.random.randn(1, 4, 8).astype(np.float32))
    out = layer(x).numpy()
    assert np.isfinite(out).all()
    assert np.abs(out).sum() > 0


def test_ring_attention_matches_dense():
    from paddle_tpu.ops.pallas.flash_attention import _ref_attention
    from paddle_tpu.ops.pallas.ring_attention import ring_attention

    mesh = dist.init_mesh(dp=1, sp=8, mp=1)
    B, H, S, D = 1, 2, 64, 8
    q = np.random.randn(B, H, S, D).astype(np.float32)
    k = np.random.randn(B, H, S, D).astype(np.float32)
    v = np.random.randn(B, H, S, D).astype(np.float32)
    ref = np.asarray(_ref_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), 1.0 / np.sqrt(D), True))

    def body(q_, k_, v_):
        return ring_attention(q_, k_, v_, axis_name="sp", causal=True)

    out = jax.shard_map(body, mesh=mesh.mesh,
                        in_specs=(P(None, None, "sp"),) * 3,
                        out_specs=P(None, None, "sp"),
                        check_vma=False)(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_backward():
    from paddle_tpu.ops.pallas.ring_attention import ring_attention
    mesh = dist.init_mesh(dp=1, sp=4, mp=1)
    B, H, S, D = 1, 2, 32, 8
    q = jnp.asarray(np.random.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(np.random.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(np.random.randn(B, H, S, D).astype(np.float32))

    def loss(q_, k_, v_):
        def body(a, b, c):
            return ring_attention(a, b, c, axis_name="sp", causal=True)
        out = jax.shard_map(body, mesh=mesh.mesh,
                            in_specs=(P(None, None, "sp"),) * 3,
                            out_specs=P(None, None, "sp"),
                            check_vma=False)(q_, k_, v_)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q, k, v)
    # compare against dense attention grads
    from paddle_tpu.ops.pallas.flash_attention import _ref_attention

    def dense_loss(q_, k_, v_):
        return jnp.sum(_ref_attention(q_, k_, v_, 1.0 / np.sqrt(D), True) ** 2)

    gd = jax.grad(dense_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gd), rtol=2e-3,
                               atol=2e-4)


def test_ulysses_matches_dense():
    from paddle_tpu.ops.pallas.flash_attention import _ref_attention
    from paddle_tpu.ops.pallas.ring_attention import ulysses_attention

    mesh = dist.init_mesh(dp=1, sp=2, mp=1)
    B, H, S, D = 1, 4, 16, 8
    q = np.random.randn(B, H, S, D).astype(np.float32)
    k = np.random.randn(B, H, S, D).astype(np.float32)
    v = np.random.randn(B, H, S, D).astype(np.float32)
    ref = np.asarray(_ref_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), 1.0 / np.sqrt(D), True))

    def body(q_, k_, v_):
        return ulysses_attention(q_, k_, v_, axis_name="sp", causal=True)

    out = jax.shard_map(body, mesh=mesh.mesh,
                        in_specs=(P(None, None, "sp"),) * 3,
                        out_specs=P(None, None, "sp"),
                        check_vma=False)(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_moe_grouped_dispatch_matches_flat_shapes():
    """group_size path: per-group capacity, one [E, G*C, D] expert batch."""
    from paddle_tpu.parallel.moe import MoELayer
    layer = MoELayer(d_model=16, num_experts=4, d_hidden=32, gate="gshard",
                     capacity_factor=2.0, group_size=8)
    x = pt.to_tensor(np.random.randn(4, 8, 16).astype(np.float32),
                     stop_gradient=False)
    out = layer(x)   # 32 tokens -> 4 groups of 8
    assert out.shape == [4, 8, 16]
    assert layer.aux_loss is not None
    (out.sum() + layer.aux_loss * 0.01).backward()
    assert layer.experts.w1.grad is not None


def test_moe_grouped_generous_capacity_matches_ungrouped():
    """With capacity large enough that nothing drops, grouped and flat
    dispatch compute the same mixture."""
    from paddle_tpu.parallel.moe import MoELayer
    pt.seed(3)
    flat = MoELayer(d_model=8, num_experts=2, d_hidden=16, gate="gshard",
                    capacity_factor=8.0)
    pt.seed(3)
    grp = MoELayer(d_model=8, num_experts=2, d_hidden=16, gate="gshard",
                   capacity_factor=8.0, group_size=4)
    for pf, pg in zip(flat.parameters(), grp.parameters()):
        pg.set_value(pf)
    x = np.random.randn(2, 8, 8).astype(np.float32)
    of = flat(pt.to_tensor(x)).numpy()
    og = grp(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(of, og, rtol=1e-4, atol=1e-5)


def test_expert_swiglu_bank():
    from paddle_tpu.parallel.moe import ExpertSwiGLU
    bank = ExpertSwiGLU(num_experts=3, d_model=8, d_hidden=16)
    x = pt.to_tensor(np.random.randn(3, 5, 8).astype(np.float32),
                     stop_gradient=False)
    out = bank(x)
    assert out.shape == [3, 5, 8]
    out.sum().backward()
    for p in (bank.w_gate, bank.w_up, bank.w_down):
        assert p.grad is not None and np.isfinite(p.grad.numpy()).all()


@pytest.mark.slow
def test_mixtral_tiny_train_step():
    """Mixtral-family model: forward, CE+aux loss, grads flow to experts."""
    from paddle_tpu.models.mixtral import MixtralForCausalLM, mixtral_tiny
    cfg = mixtral_tiny()
    m = MixtralForCausalLM(cfg)
    ids = pt.to_tensor(
        np.random.randint(0, cfg.vocab_size, (2, 64)).astype(np.int32))
    logits = m(ids)
    assert logits.shape == [2, 64, cfg.vocab_size]
    loss = m.loss(logits, ids)
    assert np.isfinite(float(loss.numpy()))
    loss.backward()
    blk = m.model.layers[0]
    assert blk.moe.experts.w_gate.grad is not None
    assert blk.moe.gate.gate.weight.grad is not None


def test_mixtral_functional_call_jit():
    """The bench path: jitted functional_call + aux loss inside the trace."""
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models.mixtral import MixtralForCausalLM, mixtral_tiny
    from paddle_tpu.core.tensor import unwrap
    cfg = mixtral_tiny(num_layers=1)
    m = MixtralForCausalLM(cfg)
    params = m.raw_params()
    ids = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32))

    def loss_of(ps):
        logits = functional_call(m, ps, ids)
        lg = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ce = -jnp.take_along_axis(lg, ids[:, 1:, None], -1).mean()
        aux = m.collect_aux_loss()
        return ce + cfg.aux_loss_coef * unwrap(aux)

    loss, grads = jax.jit(jax.value_and_grad(loss_of))(params)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0


def test_mixtral_tiny_jitted_train_updates():
    """Default-tier MoE e2e TRAIN step (VERDICT r3 weak #8): one jitted
    grad+SGD update; loss decreases over 3 reuses of the compiled step."""
    from paddle_tpu.core.tensor import unwrap
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models.mixtral import MixtralForCausalLM, mixtral_tiny
    cfg = mixtral_tiny(num_layers=1)
    m = MixtralForCausalLM(cfg)
    params = m.raw_params()
    ids = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32))

    def loss_of(ps):
        logits = functional_call(m, ps, ids)
        lg = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ce = -jnp.take_along_axis(lg, ids[:, 1:, None], -1).mean()
        aux = m.collect_aux_loss()
        return ce + cfg.aux_loss_coef * unwrap(aux)

    @jax.jit
    def step(ps):
        loss, grads = jax.value_and_grad(loss_of)(ps)
        return loss, jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g.astype(p.dtype), ps, grads)

    losses = []
    for _ in range(3):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
