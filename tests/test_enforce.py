"""Structured enforce/error system (reference enforce.h taxonomy)."""
import numpy as np
import pytest

from paddle_tpu.utils.enforce import (
    EnforceNotMet, InvalidArgumentError, NotFoundError, OutOfRangeError,
    UnimplementedError, enforce, enforce_eq, enforce_ge, enforce_gt,
    enforce_not_none, enforce_shape)


def test_typed_errors_taxonomy():
    for cls in (InvalidArgumentError, NotFoundError, OutOfRangeError,
                UnimplementedError):
        with pytest.raises(EnforceNotMet) as e:
            raise cls("boom", hint="check your inputs")
        assert cls.error_type in str(e.value)
        assert "Hint" in str(e.value)
        assert "operator stack" in str(e.value)


def test_enforce_helpers():
    assert enforce(True)
    with pytest.raises(InvalidArgumentError):
        enforce(False, "must hold")
    assert enforce_eq(3, 3)
    with pytest.raises(InvalidArgumentError, match="expected 3"):
        enforce_eq(3, 4)
    assert enforce_gt(2, 1) and enforce_ge(2, 2)
    with pytest.raises(InvalidArgumentError):
        enforce_gt(1, 2)


def test_enforce_shape_wildcards():
    x = np.zeros((5, 3, 7))
    assert enforce_shape(x, [-1, 3, 7])
    with pytest.raises(InvalidArgumentError, match="shape mismatch"):
        enforce_shape(x, [5, 4, 7], name="weight")


def test_enforce_not_none():
    assert enforce_not_none(0) == 0  # falsy but not None is fine
    with pytest.raises(NotFoundError):
        enforce_not_none(None, "scope var")
