"""cpp_extension toolchain test: JIT-build a C++ op, run it eagerly, under
jit, and through autograd (reference: test_custom_relu_op_jit.py pattern)."""
import os
import tempfile
import textwrap

import numpy as np
import pytest

SRC = textwrap.dedent("""
    #include <cstdint>
    extern "C" void square_fwd(const float* x, long long n, float* y) {
        for (long long i = 0; i < n; ++i) y[i] = x[i] * x[i];
    }
    extern "C" void square_bwd(const float* x, const float* gy,
                               long long n, float* gx) {
        for (long long i = 0; i < n; ++i) gx[i] = 2.0f * x[i] * gy[i];
    }
    extern "C" void weighted_sum(const float** ins, const long long* sizes,
                                 int n_inputs, float* out) {
        for (long long i = 0; i < sizes[0]; ++i) {
            float acc = 0.0f;
            for (int k = 0; k < n_inputs; ++k) acc += ins[k][i];
            out[i] = acc;
        }
    }
""")


@pytest.fixture(scope="module")
def ext():
    from paddle_tpu.utils import cpp_extension as cpp
    with tempfile.NamedTemporaryFile("w", suffix=".cc",
                                     delete=False) as f:
        f.write(SRC)
        path = f.name
    mod = cpp.load("test_sq_ext", [path], verbose=True)
    yield mod
    os.unlink(path)


def test_elementwise_op_forward(ext):
    import jax.numpy as jnp
    op = ext.elementwise_op("square_fwd")
    x = np.array([1.0, -2.0, 3.0], np.float32)
    np.testing.assert_allclose(np.asarray(op(jnp.asarray(x))), x * x)


def test_elementwise_op_under_jit(ext):
    import jax
    import jax.numpy as jnp
    op = ext.elementwise_op("square_fwd")
    jop = jax.jit(lambda v: op(v) + 1.0)
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    np.testing.assert_allclose(np.asarray(jop(jnp.asarray(x))),
                               x * x + 1.0)


def test_elementwise_op_grad(ext):
    import jax
    import jax.numpy as jnp
    op = ext.elementwise_op("square_fwd", grad_symbol="square_bwd")
    x = jnp.asarray([1.0, -2.0, 3.0])
    g = jax.grad(lambda v: op(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), [2.0, -4.0, 6.0])


def test_custom_multi_input_op(ext):
    import jax.numpy as jnp
    op = ext.custom_op("weighted_sum", n_inputs=3)
    a = np.ones((4,), np.float32)
    b = np.full((4,), 2.0, np.float32)
    c = np.full((4,), 3.0, np.float32)
    out = op(jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))
    np.testing.assert_allclose(np.asarray(out), 6.0)


def test_missing_symbol_raises(ext):
    with pytest.raises(AttributeError, match="no symbol"):
        ext.elementwise_op("nope_fn")


def test_compile_error_raises():
    from paddle_tpu.utils import cpp_extension as cpp
    with tempfile.NamedTemporaryFile("w", suffix=".cc",
                                     delete=False) as f:
        f.write("this is not C++")
        path = f.name
    with pytest.raises(RuntimeError, match="compilation"):
        cpp.load("broken_ext", [path])
    os.unlink(path)


def test_integration_with_framework_autograd(ext):
    """Custom op inside a paddle_tpu train step."""
    import paddle_tpu as pt
    from paddle_tpu.core.tensor import dispatch
    op = ext.elementwise_op("square_fwd", grad_symbol="square_bwd")
    x = pt.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    y = dispatch(op, x, name="custom_square")
    s = dispatch(lambda v: v.sum(), y, name="sum")
    s.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])
