"""Speculative decoding (inference/speculative.py): greedy draft-and-
verify must be BIT-IDENTICAL to the target model's own greedy decode —
speculation may only change how many target forwards run."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.speculative import speculative_generate


def _llama(seed):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(seed)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


class TestSpeculative:
    def test_perfect_draft_accepts_gamma_every_round(self):
        """Draft == target: every proposal matches, each round yields
        gamma+1 tokens."""
        model = _llama(51)
        rng = np.random.default_rng(7)
        ids = rng.integers(0, 256, (1, 5)).astype(np.int32)
        want = model.generate(pt.to_tensor(ids), max_new_tokens=12,
                              max_cache_len=64).numpy()
        got, stats = speculative_generate(
            model, model, pt.to_tensor(ids), max_new_tokens=12,
            gamma=3, max_cache_len=64, return_stats=True)
        np.testing.assert_array_equal(got.numpy()[:, :want.shape[1]],
                                      want)
        assert stats["mean_accepted"] == 3.0, stats

    def test_weak_draft_still_exact(self):
        """A DIFFERENT draft model (other init) mostly mismatches — the
        output must still equal the target's own greedy decode."""
        target = _llama(52)
        draft = _llama(53)
        rng = np.random.default_rng(8)
        ids = rng.integers(0, 256, (1, 4)).astype(np.int32)
        want = target.generate(pt.to_tensor(ids), max_new_tokens=10,
                               max_cache_len=64).numpy()
        got, stats = speculative_generate(
            target, draft, pt.to_tensor(ids), max_new_tokens=10,
            gamma=4, max_cache_len=64, return_stats=True)
        np.testing.assert_array_equal(got.numpy()[:, :want.shape[1]],
                                      want)
        # weak draft: strictly fewer accepts than perfect drafting
        assert stats["mean_accepted"] < 4.0

    def test_cross_family_draft(self):
        """GPT drafting for Llama (shared tiny vocab): exactness does not
        depend on the draft architecture."""
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        target = _llama(54)
        pt.seed(55)
        draft = GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=32,
                                         num_layers=1, num_heads=2,
                                         max_seq_len=64))
        draft.eval()
        rng = np.random.default_rng(9)
        ids = rng.integers(0, 256, (1, 4)).astype(np.int32)
        want = target.generate(pt.to_tensor(ids), max_new_tokens=8,
                               max_cache_len=64).numpy()
        got = speculative_generate(target, draft, pt.to_tensor(ids),
                                   max_new_tokens=8, gamma=2,
                                   max_cache_len=64)
        np.testing.assert_array_equal(got.numpy()[:, :want.shape[1]],
                                      want)

    def test_eos_stops_early(self):
        model = _llama(56)
        rng = np.random.default_rng(10)
        ids = rng.integers(0, 256, (1, 4)).astype(np.int32)
        ref = model.generate(pt.to_tensor(ids), max_new_tokens=10,
                             max_cache_len=64).numpy()[0, 4:]
        eos = int(ref[3])
        want = model.generate(pt.to_tensor(ids), max_new_tokens=10,
                              eos_token_id=eos,
                              max_cache_len=64).numpy()[0]
        got = speculative_generate(model, model, pt.to_tensor(ids),
                                   max_new_tokens=10, gamma=2,
                                   eos_token_id=eos,
                                   max_cache_len=64).numpy()[0]
        # full bit-identity incl. the eos-padded tail (generate contract)
        np.testing.assert_array_equal(got, want)

    def test_headroom_guard(self):
        model = _llama(57)
        with pytest.raises(ValueError, match="headroom"):
            speculative_generate(model, model,
                                 np.zeros((1, 50), np.int32),
                                 max_new_tokens=10, gamma=4,
                                 max_cache_len=64)
