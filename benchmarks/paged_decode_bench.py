"""Dense vs paged KV-cache continuous-batching decode (ISSUE 1).

Drives the same mixed-length workload — request budgets spanning
32..max_cache_len tokens in one slot pool — through
``ContinuousBatchingServer`` with ``cache_backend="dense"`` and
``"paged"`` and reports:

- decode throughput (generated tokens / wall-clock drain time),
- cache HBM: the dense backend allocates ``slots x max_cache_len`` rows
  up front; the paged pool is sized to the worst-case CONCURRENT token
  working set (sum of the largest ``max_slots`` request extents), so its
  footprint tracks actual tokens,
- decode-program compile count across slot churn (the block table is a
  runtime argument — it must stay at 1),
- token parity (the paged backend is bit-identical on the XLA path),
- steady-state GOODPUT ratio per mode (ISSUE 11: the goodput ledger's
  useful / total device tokens — the paged backend trades dense HBM
  for masked page DMAs the ledger makes visible),
- the FUSED serving tick (ISSUE 14, ``serving_mode="fused"``): one
  launch per tick over a live-page DMA schedule — tokens/s, goodput
  ratio (the acceptance bar: >= 10x the split paged ratio, because
  ``skipped_page_dma`` collapses to the schedule's ladder pad and
  ``null_redirect`` to zero), dispatches per tick, and the fused
  program's compiled FLOPs/HBM-bytes per token next to the split
  decode program's.

- the SHARDED paged column (ISSUE 16, ``--mesh``): the same paged
  workload served with the K/V pool sharded on the kv-head dim over a
  tensor-parallel mesh at mp in {1, 2, 4} — tokens/s, compiled decode
  HBM B/tok per shard, the measured per-device pool-byte fraction, and
  token parity vs the mp=1 run. On CPU the mesh pays real collective
  overhead per tick; the column is recorded honestly (capacity is the
  win — per-device pool bytes — not CPU throughput).

    python benchmarks/paged_decode_bench.py [--model tiny|350m]
        [--slots N] [--cache-len N] [--page-size N] [--track] [--mesh]
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _mixed_requests(rng, max_cache_len, n_requests):
    """Prompt/budget pairs whose total extents sweep 32..max_cache_len."""
    reqs = []
    total = 32
    for i in range(n_requests):
        prompt = int(rng.integers(8, 24))
        new = max(1, total - prompt)
        reqs.append((rng.integers(0, 256, (prompt,)).astype(np.int32),
                     new))
        total = min(total * 2, max_cache_len)
        if total == max_cache_len:
            total = 32 + int(rng.integers(0, 64))
    return reqs


def _warm_reqs(reqs, rng):
    """Same (prompt_len, budget) pairs — so the warm drain visits the
    same compile-geometry ladder points — but FRESH tokens, so the
    auto prefix cache stays cold for the timed drain."""
    return [(rng.integers(0, 256, (len(p),)).astype(np.int32), n)
            for p, n in reqs]


def _drain(srv, reqs, warm=None):
    if warm is not None:
        # untimed compile-warm pass: tokens/s below measures the
        # steady state, not XLA (the ladder compile counts are still
        # reported from the cost catalog)
        for p, n in warm:
            srv.submit(p, max_new_tokens=n)
        srv.run()
    t0 = time.perf_counter()
    rids = [srv.submit(p, max_new_tokens=n) for p, n in reqs]
    outs = srv.run()
    dt = time.perf_counter() - t0
    toks = sum(len(outs[r]) for r in rids)
    return [outs[r] for r in rids], toks, dt


def main(model_name="tiny", slots=4, cache_len=1024, page_size=16,
         n_requests=12, track=False):
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    from paddle_tpu.inference.kv_cache import PagedKVCache
    from paddle_tpu.models.llama import (LlamaForCausalLM, llama_350m,
                                         llama_tiny)
    from paddle_tpu.telemetry import CostCatalog, GoodputLedger

    pt.seed(7)
    cfg = (llama_tiny if model_name == "tiny" else llama_350m)(
        max_seq_len=max(cache_len, 128))
    model = LlamaForCausalLM(cfg)
    model.eval()
    L, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    itemsize = jnp.dtype(cfg.dtype).itemsize

    rng = np.random.default_rng(0)
    reqs = _mixed_requests(rng, cache_len, n_requests)
    warm = _warm_reqs(reqs, rng)
    extents = sorted((len(p) + n for p, n in reqs), reverse=True)
    # pool = worst-case concurrent working set (+1 null page, + one
    # page per slot of block-boundary slack)
    work_tokens = sum(extents[:slots])
    num_pages = -(-work_tokens // page_size) + slots + 1
    print(f"workload: {n_requests} requests, extents 32..{cache_len} "
          f"(peak concurrent {work_tokens} tokens), {slots} slots")

    led_d = GoodputLedger()
    dense = ContinuousBatchingServer(model, max_slots=slots,
                                     max_cache_len=cache_len,
                                     ledger=led_d)
    outs_d, toks_d, dt_d = _drain(dense, reqs, warm=warm)
    hbm_d = PagedKVCache.dense_hbm_bytes(slots, cache_len, L, kvh, hd,
                                         itemsize)
    good_d = led_d.snapshot()
    print(f"dense: {toks_d / dt_d:8,.0f} tok/s   "
          f"cache HBM {hbm_d / 2**20:8.2f} MiB "
          f"({slots} slots x {cache_len} rows)   "
          f"goodput {good_d['goodput_ratio']:.3f}")

    led_p = GoodputLedger()
    cat = CostCatalog()               # device-cost ledger (ISSUE 13)
    paged = ContinuousBatchingServer(model, max_slots=slots,
                                     max_cache_len=cache_len,
                                     cache_backend="paged",
                                     page_size=page_size,
                                     num_pages=num_pages,
                                     ledger=led_p, costs=cat)
    outs_p, toks_p, dt_p = _drain(paged, reqs, warm=warm)
    hbm_p = PagedKVCache.paged_hbm_bytes(num_pages, page_size, L, kvh,
                                         hd, itemsize)
    # the costed dispatch path runs the catalog's AOT executable
    # (priced once, cached on the server), so the jit cache is idle
    # and a decode shape leak can no longer recompile SILENTLY — it
    # would fail the dispatch loudly. compiles == 1 verifies decode
    # stayed one program; the catalog's post-warmup `recompiles`
    # counter (printed below) is the live churn signal for the
    # prefill chunk-width ladder
    compiles = cat.compiles().get("decode", 0)
    good_p = led_p.snapshot()
    print(f"paged: {toks_p / dt_p:8,.0f} tok/s   "
          f"cache HBM {hbm_p / 2**20:8.2f} MiB "
          f"({num_pages} pages x {page_size} rows, "
          f"{hbm_d / hbm_p:.1f}x smaller)   "
          f"goodput {good_p['goodput_ratio']:.3f}")
    waste_p = {k: v for k, v in sorted(good_p["tokens"].items())
               if k != "goodput"}
    print(f"paged waste breakdown (tokens): {waste_p}")
    print(f"decode compiles across slot churn: {compiles} (want 1)")
    # device-cost baseline (ISSUE 13): the compiled decode program's
    # own price per generated token — THE roofline numbers the fused
    # megakernel (ROADMAP item 2) must beat
    costs = cat.snapshot()
    dec = costs["ops"].get("decode", {"flops": 0.0, "hbm_bytes": 0.0,
                                      "dispatches": 0})
    # catalog totals span the warm + timed drains; per-token divides
    # by ALL generated tokens (no eos in this workload, so the warm
    # drain generated exactly its budgets)
    warm_toks = sum(n for _, n in warm)
    flops_tok = dec["flops"] / max(toks_p + warm_toks, 1)
    bytes_tok = dec["hbm_bytes"] / max(toks_p + warm_toks, 1)
    mfu = costs["mfu"] if costs["mfu"] is not None else 0.0
    print(f"device cost (compiled decode program): "
          f"{flops_tok:10,.0f} FLOPs/tok  {bytes_tok:10,.0f} HBM B/tok  "
          f"mfu {mfu:.4f}  roofline {costs['roofline_ratio'] or 0:.4f} "
          f"(placeholder peaks; compiles {costs['compiles']}, "
          f"recompiles {costs['recompiles']})")
    parity = all(np.array_equal(a, b) for a, b in zip(outs_d, outs_p))
    print(f"token parity dense vs paged: {parity}")
    if hbm_d < 2 * hbm_p:
        print("WARNING: <2x HBM reduction — workload not mixed enough?")

    # ------------------------------------------------ fused serving tick
    led_f = GoodputLedger()
    cat_f = CostCatalog()
    fused = ContinuousBatchingServer(model, max_slots=slots,
                                     max_cache_len=cache_len,
                                     cache_backend="paged",
                                     page_size=page_size,
                                     num_pages=num_pages,
                                     serving_mode="fused",
                                     ledger=led_f, costs=cat_f)
    outs_f, toks_f, dt_f = _drain(fused, reqs, warm=warm)
    good_f = led_f.snapshot()
    print(f"fused: {toks_f / dt_f:8,.0f} tok/s   "
          f"cache HBM {hbm_p / 2**20:8.2f} MiB (same pool)   "
          f"goodput {good_f['goodput_ratio']:.3f}")
    waste_f = {k: v for k, v in sorted(good_f["tokens"].items())
               if k != "goodput"}
    print(f"fused waste breakdown (tokens): {waste_f}")
    disp_tick = fused.stats["tick_dispatches"]
    print(f"fused dispatches: {disp_tick} across warm + timed drains "
          f"(one per tick; split admission ticks add prefill + "
          f"state_push + block_table on top of decode)")
    costs_f = cat_f.snapshot()
    fop = costs_f["ops"].get("fused", {"flops": 0.0, "hbm_bytes": 0.0})
    print(f"device cost (compiled fused program):  "
          f"{fop['flops'] / max(toks_f + warm_toks, 1):10,.0f} "
          f"FLOPs/tok  "
          f"{fop['hbm_bytes'] / max(toks_f + warm_toks, 1):10,.0f} "
          f"HBM B/tok  (compiles {costs_f['compiles']} on the "
          f"geometry ladder, recompiles {costs_f['recompiles']})")
    ratio_gain = good_f["goodput_ratio"] / max(good_p["goodput_ratio"],
                                               1e-9)
    parity_f = all(np.array_equal(a, b) for a, b in zip(outs_d, outs_f))
    print(f"token parity dense vs fused: {parity_f}")
    fused_ok = parity_f and ratio_gain >= 10.0
    print(f"goodput gain fused/split: {ratio_gain:,.0f}x "
          f"({'OK' if ratio_gain >= 10.0 else 'REGRESSION'}; "
          f"ISSUE 14 acceptance bar is 10x)")
    if track:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_track", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "scripts", "bench_track.py"))
        bench_track = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench_track)
        note = (f"{model_name} model, {slots} slots, cache {cache_len},"
                f" pg {page_size}; compiled-program pricing, "
                f"placeholder peaks")
        for metric, value, unit in (
                ("paged_decode_tokens_per_sec", toks_p / dt_p,
                 "tokens/s"),
                ("paged_decode_flops_per_token", flops_tok, "flops"),
                ("paged_decode_hbm_bytes_per_token", bytes_tok,
                 "bytes"),
                ("paged_decode_mfu", mfu, "ratio"),
                ("fused_decode_tokens_per_sec", toks_f / dt_f,
                 "tokens/s"),
                ("fused_paged_goodput_ratio", good_f["goodput_ratio"],
                 "ratio")):
            r = bench_track.append_round(
                {"metric": metric, "value": value, "unit": unit,
                 "note": note})
            print(f"tracked {r['metric']} = {r['value']}")
    return 0 if parity and fused_ok else 1


def _track_rounds(rows, note):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_track", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "bench_track.py"))
    bench_track = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_track)
    for metric, value, unit in rows:
        r = bench_track.append_round(
            {"metric": metric, "value": value, "unit": unit,
             "note": note})
        print(f"tracked {r['metric']} = {r['value']}")


def mesh_main(slots=4, cache_len=256, page_size=16, n_requests=8,
              track=False):
    """``--mesh``: the sharded paged serving column (ISSUE 16).

    Same mixed workload through a paged server at mp in {1, 2, 4} on a
    kv-head-divisible tiny llama (4 kv heads — llama_tiny's 2 would cap
    sharding at mp=2). The mp=1 run is the oracle: every mesh run must
    emit identical tokens. Reported per mp: compile-warmed tokens/s,
    the compiled decode program's HBM bytes per token PER SHARD
    (catalog global bytes / shard count), and the measured per-device
    pool bytes as a fraction of the mp=1 pool."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.telemetry import CostCatalog

    if len(jax.devices()) < 4:
        print(f"--mesh needs >= 4 devices, have {len(jax.devices())} "
              f"(run under XLA_FLAGS="
              f"--xla_force_host_platform_device_count=8)")
        return 1
    from jax.sharding import Mesh

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=8, num_kv_heads=4,
                      intermediate_size=128,
                      max_seq_len=max(cache_len, 128))
    pt.seed(7)
    model = LlamaForCausalLM(cfg)
    model.eval()

    rng = np.random.default_rng(0)
    reqs = _mixed_requests(rng, cache_len, n_requests)
    warm = _warm_reqs(reqs, rng)
    warm_toks = sum(n for _, n in warm)
    extents = sorted((len(p) + n for p, n in reqs), reverse=True)
    work_tokens = sum(extents[:slots])
    num_pages = -(-work_tokens // page_size) + slots + 1
    print(f"sharded paged column: {n_requests} requests, extents "
          f"32..{cache_len}, {slots} slots, {num_pages} pages x "
          f"{page_size} rows, 4 kv heads")

    results = {}
    for mp in (1, 2, 4):
        cat = CostCatalog()
        mesh = None if mp == 1 else Mesh(np.array(jax.devices()[:mp]),
                                         ("mp",))
        srv = ContinuousBatchingServer(model, max_slots=slots,
                                       max_cache_len=cache_len,
                                       cache_backend="paged",
                                       page_size=page_size,
                                       num_pages=num_pages, mesh=mesh,
                                       costs=cat)
        outs, toks, dt = _drain(srv, reqs, warm=warm)
        shards = srv._pool_shards
        op = "decode" if shards <= 1 else f"decode_mp{shards}"
        dec = cat.snapshot()["ops"].get(op, {"hbm_bytes": 0.0})
        bytes_tok_shard = dec["hbm_bytes"] / max(toks + warm_toks, 1) \
            / max(shards, 1)
        shard_bytes = srv._shard_pool_bytes()
        results[mp] = dict(outs=outs, toks_s=toks / dt,
                           bytes_tok_shard=bytes_tok_shard,
                           shard_bytes=shard_bytes,
                           compiles=cat.compiles().get(op, 0),
                           recompiles=cat.recompiles)
        frac = shard_bytes / results[1]["shard_bytes"]
        # decode compiles == 1 is the steady-state gate: the sharded
        # decode signature is static across slot churn. The catalog's
        # `recompiles` counter also ticks on prefill chunk-width LADDER
        # DISCOVERY (a cold catalog warms on the first width, then
        # meets the next) — printed for honesty, not gated
        print(f"mp={mp}: {toks / dt:8,.0f} tok/s   "
              f"decode HBM/shard {bytes_tok_shard:10,.0f} B/tok   "
              f"pool bytes/device {shard_bytes / 2**20:6.2f} MiB "
              f"({frac:.3f}x of mp=1)   "
              f"decode compiles {results[mp]['compiles']} (ladder "
              f"recompiles {results[mp]['recompiles']})")

    parity = all(
        np.array_equal(a, b)
        for mp in (2, 4)
        for a, b in zip(results[1]["outs"], results[mp]["outs"]))
    frac4 = results[4]["shard_bytes"] / results[1]["shard_bytes"]
    print(f"token parity mp=2/mp=4 vs mp=1: {parity}")
    print(f"per-device pool bytes at mp=4: {frac4:.3f}x of mp=1 "
          f"(want <= 0.25 + block-boundary epsilon)")
    ok = parity and frac4 <= 0.3 \
        and all(r["compiles"] == 1 for r in results.values())
    if track:
        note = (f"tiny 4-kv-head llama, {slots} slots, cache "
                f"{cache_len}, pg {page_size}; CPU forced-host mesh — "
                f"collective overhead included, capacity (pool "
                f"bytes/device) is the win")
        _track_rounds(
            [(f"sharded_paged_decode_tokens_per_sec_mp{mp}",
              results[mp]["toks_s"], "tokens/s") for mp in (1, 2, 4)]
            + [("sharded_paged_decode_hbm_bytes_per_token_per_shard_mp4",
                results[4]["bytes_tok_shard"], "bytes"),
               ("sharded_paged_pool_bytes_frac_mp4", frac4, "ratio")],
            note)
    return 0 if ok else 1


if __name__ == "__main__":
    kw = {}
    argv = sys.argv[1:]
    if "--mesh" in argv:
        # the forced host-device env must land BEFORE jax initializes
        # (mesh_main imports jax lazily, so setting it here works)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if "--model" in argv:
        kw["model_name"] = argv[argv.index("--model") + 1]
    if "--slots" in argv:
        kw["slots"] = int(argv[argv.index("--slots") + 1])
    if "--cache-len" in argv:
        kw["cache_len"] = int(argv[argv.index("--cache-len") + 1])
    if "--page-size" in argv:
        kw["page_size"] = int(argv[argv.index("--page-size") + 1])
    if "--track" in argv:             # append this round to BENCHLOG
        kw["track"] = True
    if "--mesh" in argv:
        kw.pop("model_name", None)
        sys.exit(mesh_main(**kw))
    sys.exit(main(**kw))
