"""A/B microbench: GPT-2 345M train step, materialized-logits CE vs fused
chunked linear+CE (ops/fused_ce.py). Run on the real TPU chip:

    python benchmarks/fused_ce_bench.py [batch] [chunk]
"""
import sys
import time

import numpy as np


def main(batch=8, chunk=2046):
    import contextlib

    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt2_345m
    from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy

    seq = 1024
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except Exception:
        cpu = None
    with (jax.default_device(cpu) if cpu is not None
          else contextlib.nullcontext()):
        cfg = gpt2_345m(dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.astype("bfloat16")
        model.eval()
        opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
        init_fn, update_fn = opt.functional()
        params = model.raw_params()
        state = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32), init_fn(params))
    dev = jax.devices()[0]
    params = jax.device_put(params, dev)
    state = jax.device_put(state, dev)
    ids = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        dev)

    def loss_materialized(ps):
        logits = functional_call(model, ps, ids)
        lg = logits[:, :-1]
        lb = ids[:, 1:]
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        return -jnp.take_along_axis(logp, lb[..., None], -1).mean()

    def loss_fused(ps):
        hidden = functional_call(model, ps, ids, return_hidden=True)
        w = ps["lm_head_weight"].T          # tied head: [V,H] -> [H,V]
        return fused_linear_cross_entropy(hidden[:, :-1], w, ids[:, 1:],
                                          chunk_size=chunk)

    results = {}
    for name, loss_fn in (("materialized", loss_materialized),
                          ("fused", loss_fused)):
        def step(params, state, i, _loss=loss_fn):
            loss, grads = jax.value_and_grad(_loss)(params)
            new_p, new_s = update_fn(grads, params, state, step=i)
            return loss, new_p, new_s

        jstep = jax.jit(step, donate_argnums=(0, 1))
        p, s = params, state
        loss, p, s = jstep(p, s, 1)
        float(loss)
        loss, p, s = jstep(p, s, 2)
        float(loss)
        iters = 10
        t0 = time.perf_counter()
        for i in range(iters):
            loss, p, s = jstep(p, s, i + 3)
        lv = float(loss)
        dt = (time.perf_counter() - t0) / iters
        toks = batch * seq / dt
        results[name] = (dt * 1000, toks)
        print(f"{name}: {dt*1000:.1f} ms/step, {toks:,.0f} tok/s, "
              f"loss={lv:.4f}", flush=True)
        # refresh donated buffers for the next variant
        params = jax.device_put(model.raw_params(), dev)
        state = jax.device_put(jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32),
            pt.optimizer.AdamW(learning_rate=1e-4,
                               parameters=model.parameters())
            .functional()[0](model.raw_params())), dev)

    m, f = results["materialized"][0], results["fused"][0]
    print(f"speedup: {m / f:.3f}x")


if __name__ == "__main__":
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    c = int(sys.argv[2]) if len(sys.argv) > 2 else 2046
    main(b, c)
