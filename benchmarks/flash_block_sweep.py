"""Sweep Pallas flash-attention block sizes on the TPU chip."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def main():
    from benchmarks.flash_microbench import _devices_with_retry, bench
    from paddle_tpu.ops.pallas import flash_attention as fa

    _devices_with_retry()
    rng = np.random.RandomState(0)
    d = 128
    # (label, batch*heads, seq): MHA 345M-ish shapes; the 70B TP8 local
    # slice (GQA kv pre-repeated to 8 local q heads, small batch); the
    # 32k long-context shard (VERDICT r4 weak#1 — GQA/longctx shapes
    # were never swept on chip)
    shapes = [("mha-4k  (bh=64)", 64, 4096),
              ("gqa70b-4k (bh=8)", 8, 4096),
              ("longctx-32k (bh=8)", 8, 32768)]
    for label, bh, s in shapes:
        mk = lambda: jnp.asarray(
            rng.randn(bh, s, d).astype(np.float32) * 0.3,
            dtype=jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        sm = 1.0 / np.sqrt(d)
        f_fwd = 2.0 * bh * s * s * d
        f_bwd = 5.0 * bh * s * s * d
        print(f"== {label} ==")
        for bq, bk in [(512, 512), (1024, 1024), (512, 2048),
                       (1024, 2048), (2048, 1024), (2048, 2048),
                       (1024, 4096)]:
            if bq > s or bk > s:
                continue
            try:
                fwd = jax.jit(
                    lambda q, k, v, bq=bq, bk=bk: fa._flash_fwd_pallas(
                        q, k, v, sm, True, block_q=bq, block_k=bk)[0])
                t_f = bench(fwd, q, k, v, iters=10)

                def bwd(q, k, v, bq=bq, bk=bk):
                    o, lse = fa._flash_fwd_pallas(q, k, v, sm, True,
                                                  block_q=bq, block_k=bk)
                    return fa._flash_bwd_pallas(q, k, v, o, lse, q, sm,
                                                True, block_q=bq,
                                                block_k=bk)

                t_b = bench(jax.jit(bwd), q, k, v, iters=10)
                print(f"bq={bq:4d} bk={bk:4d}  fwd {t_f*1e3:7.2f}ms "
                      f"({f_fwd/t_f/1e12:5.1f} TF/s)   fwd+bwd "
                      f"{t_b*1e3:7.2f}ms "
                      f"({(f_fwd+f_bwd)/t_b/1e12:5.1f} TF/s)")
            except Exception as e:
                print(f"bq={bq} bk={bk}  FAILED: {str(e)[:120]}")


if __name__ == "__main__":
    main()
