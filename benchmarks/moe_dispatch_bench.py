"""MoE dispatch A/B on chip (VERDICT r5 #5).

Reference ships CUDA global_scatter/global_gather
(paddle/fluid/operators/collective/global_scatter_op.cu.cc) — a
sort-based sparse dispatch. Our hybrid MoE block uses DENSE GShard-style
dispatch (every expert computes every token on the MXU; combine selects)
which burns E/k extra FLOPs but has zero gather/scatter. This bench
measures both at Mixtral-8x7B per-chip shapes to pick the default by
measurement:

  dense:  einsum over the full [E, T, F] — E x T x H x F FLOPs
  sorted: top-k gather to [E, C, H] capacity bins, expert matmuls,
          weighted scatter-add back — k x T x H x F FLOPs + data movement

Prints ms/step and us/token for each; the winner should drive
make_moe_tp_fns' dispatch choice.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def bench(fn, *args, iters=10):
    out = fn(*args)
    jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    return (time.perf_counter() - t0) / iters


def main():
    # Mixtral-8x7B per-chip: H=4096, expert FFN 14336, E=8, top-2.
    # T tokens on this chip (batch x seq shard).
    T, H, F, E, K = 4096, 4096, 14336, 8, 2
    cap_factor = 1.25
    C = int(T * K / E * cap_factor)
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16

    x = jnp.asarray(rng.randn(T, H).astype(np.float32) * 0.3, dt)
    wg = jnp.asarray(rng.randn(H, E).astype(np.float32) * 0.1, dt)
    we_g = jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.02, dt)
    we_u = jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.02, dt)
    we_d = jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.02, dt)

    def gate(xv):
        logits = xv @ wg
        topv, topi = jax.lax.top_k(logits.astype(jnp.float32), K)
        probs = jax.nn.softmax(topv, -1)
        return probs, topi

    # ---- dense GShard-style (the hybrid block's current dispatch) ----
    @jax.jit
    def dense(xv):
        probs, topi = gate(xv)
        oh = jax.nn.one_hot(topi, E, dtype=jnp.float32)
        comb = (oh * probs[..., None]).sum(-2)           # [T, E]
        up = jnp.einsum("th,ehf->etf", xv, we_g)
        up = jax.nn.silu(up) * jnp.einsum("th,ehf->etf", xv, we_u)
        down = jnp.einsum("etf,efh->eth", up, we_d)
        return jnp.einsum("eth,te->th", down.astype(jnp.float32),
                          comb).astype(xv.dtype)

    # ---- sort/capacity dispatch (reference global_scatter shape) -----
    @jax.jit
    def sorted_dispatch(xv):
        probs, topi = gate(xv)                            # [T, K]
        flat_e = topi.reshape(-1)                         # [T*K]
        flat_w = probs.reshape(-1)                        # [T*K]
        flat_t = jnp.repeat(jnp.arange(T), K)
        # sort pairs by expert; rank within each expert's run = bin slot
        order = jnp.argsort(flat_e, stable=True)
        sorted_e = flat_e[order]
        run_start = jnp.cumsum(
            jnp.concatenate([jnp.zeros(1, jnp.int32),
                             jnp.bincount(sorted_e, length=E)[:-1]
                             .astype(jnp.int32)]))
        rank = jnp.arange(T * K) - run_start[sorted_e]
        keep = rank < C                                   # capacity drop
        # dropped pairs land in a SCRATCH slot — routing them to slot
        # C-1 would clobber a legitimately binned token
        dst = jnp.where(keep, sorted_e * C + rank, E * C)
        src_tok = flat_t[order]
        bins = jnp.zeros((E * C + 1, H), xv.dtype)
        bins = bins.at[dst].set(jnp.where(keep[:, None], xv[src_tok], 0))
        eb = bins[:E * C].reshape(E, C, H)
        up = jnp.einsum("ech,ehf->ecf", eb, we_g)
        up = jax.nn.silu(up) * jnp.einsum("ech,ehf->ecf", eb, we_u)
        down = jnp.einsum("ecf,efh->ech", up, we_d).reshape(E * C, H)
        out = jnp.zeros((T, H), jnp.float32)
        w_sorted = flat_w[order]
        picked = down[jnp.minimum(dst, E * C - 1)]
        out = out.at[src_tok].add(
            jnp.where(keep[:, None],
                      picked.astype(jnp.float32) * w_sorted[:, None],
                      0.0))
        return out.astype(xv.dtype)

    t_dense = bench(dense, x)
    t_sorted = bench(sorted_dispatch, x)
    fl_dense = 3 * 2 * T * H * F * E       # 3 matmuls, all experts
    fl_sorted = 3 * 2 * T * H * F * K      # only routed pairs (capacity)
    print(f"tokens={T} H={H} F={F} E={E} top{K} capacity={C}")
    print(f"dense  GShard : {t_dense*1e3:8.2f} ms/step  "
          f"{t_dense/T*1e6:6.2f} us/token  "
          f"({fl_dense/t_dense/1e12:5.1f} TF/s effective)")
    print(f"sorted capac. : {t_sorted*1e3:8.2f} ms/step  "
          f"{t_sorted/T*1e6:6.2f} us/token  "
          f"({fl_sorted/t_sorted/1e12:5.1f} TF/s effective)")
    win = "dense" if t_dense <= t_sorted else "sorted"
    print(f"winner: {win} ({max(t_dense, t_sorted)/min(t_dense, t_sorted):.2f}x)")


if __name__ == "__main__":
    main()
