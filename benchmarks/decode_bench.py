"""Autoregressive decode throughput on the real chip (VERDICT r3 #6).

KV-cache decode through incubate fused_multi_transformer's STATIC-cache
path (time_step + dynamic_update_slice — one compiled step for every
position; reference fused_multi_transformer_op.cu serving path), plus an
int8 weight-only variant over the Pallas quantized_matmul kernel.

Prints one line per config: decode tokens/s (batch x new tokens / wall).

    python benchmarks/decode_bench.py [--steps N]
"""
import sys
import time

import numpy as np


def main(steps=128):
    import jax
    import jax.numpy as jnp

    import paddle_tpu.incubate.nn.functional as IF

    # GPT-2 345M shape: 24 layers, 1024 hidden, 16 heads
    L, D, H, FF = 24, 1024, 16, 4096
    B, T_PRE, T_MAX = 8, 512, 1024
    rng = np.random.default_rng(0)
    dt = jnp.bfloat16

    def mk(*s):
        return jnp.asarray(rng.standard_normal(s).astype("float32") * 0.02,
                           dt)

    weights = dict(
        ln_scales=[jnp.ones((D,), dt) for _ in range(L)],
        ln_biases=[jnp.zeros((D,), dt) for _ in range(L)],
        qkv_weights=[mk(D, 3 * D) for _ in range(L)],
        qkv_biases=[jnp.zeros((3 * D,), dt) for _ in range(L)],
        linear_weights=[mk(D, D) for _ in range(L)],
        linear_biases=[jnp.zeros((D,), dt) for _ in range(L)],
        ffn_ln_scales=[jnp.ones((D,), dt) for _ in range(L)],
        ffn_ln_biases=[jnp.zeros((D,), dt) for _ in range(L)],
        ffn1_weights=[mk(D, FF) for _ in range(L)],
        ffn1_biases=[jnp.zeros((FF,), dt) for _ in range(L)],
        ffn2_weights=[mk(FF, D) for _ in range(L)],
        ffn2_biases=[jnp.zeros((D,), dt) for _ in range(L)],
    )
    n_params = sum(int(np.prod(w.shape)) for ws in weights.values()
                   for w in ws)

    def step_fn(x, caches, t, ws):
        out, new_caches = IF.fused_multi_transformer(
            x, num_heads=H, trans_qkvw=False, cache_kvs=caches,
            time_step=t, **ws)
        return out, new_caches

    jit_step = jax.jit(step_fn, donate_argnums=(1,))

    caches = [jnp.zeros((2, B, H, T_MAX, D // H), dt) for _ in range(L)]
    x_pre = mk(B, T_PRE, D)
    x_dec = mk(B, 1, D)

    # prefill (chunked-prefill path at t=0)
    t0 = time.perf_counter()
    out, caches = jit_step(x_pre, caches, jnp.int32(0), weights)
    out.block_until_ready()
    t_prefill = time.perf_counter() - t0

    # warm the decode-shape compile
    out, caches = jit_step(x_dec, caches, jnp.int32(T_PRE), weights)
    out.block_until_ready()

    t0 = time.perf_counter()
    for i in range(1, steps):
        out, caches = jit_step(x_dec, caches, jnp.int32(T_PRE + i), weights)
    out.block_until_ready()
    dt_dec = time.perf_counter() - t0
    toks = B * (steps - 1) / dt_dec
    print(f"bf16 decode: {toks:,.0f} tok/s "
          f"({dt_dec / (steps - 1) * 1000:.2f} ms/step, B={B}, "
          f"{n_params / 1e6:.0f}M params, prefill {T_PRE} in "
          f"{t_prefill:.2f}s)", flush=True)

    # ---- int8 weight-only variant over Pallas quantized_matmul ---------
    from paddle_tpu.ops.pallas.quant_matmul import (available,
                                                    quantized_matmul,
                                                    quantize_tensor)
    if not available():
        print("int8 decode: skipped (no TPU pallas)", flush=True)
        return

    qw = {}
    for key in ("qkv_weights", "linear_weights", "ffn1_weights",
                "ffn2_weights"):
        qw[key] = [quantize_tensor(w.astype(jnp.float32),
                                   per_channel_axis=1)
                   for w in weights[key]]

    def qmm(x2d, wq):
        w_i8, s_w = wq
        x_q, s_x = quantize_tensor(x2d.astype(jnp.float32),
                                   per_channel_axis=0)
        return quantized_matmul(x_q, w_i8, s_x, s_w)

    def int8_step(x, caches, t):
        b, s, _ = x.shape
        out = x
        new_caches = []
        for i in range(L):
            res = out
            h = _ln(out, weights["ln_scales"][i], weights["ln_biases"][i])
            qkv = qmm(h.reshape(b * s, D),
                      qw["qkv_weights"][i]).reshape(b, s, 3 * D)
            qkv = (qkv + weights["qkv_biases"][i]).reshape(
                b, s, 3, H, D // H)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            c = caches[i]
            kt = jnp.transpose(k, (0, 2, 1, 3)).astype(c.dtype)
            vt = jnp.transpose(v, (0, 2, 1, 3)).astype(c.dtype)
            ck = jax.lax.dynamic_update_slice_in_dim(c[0], kt, t, 2)
            cv = jax.lax.dynamic_update_slice_in_dim(c[1], vt, t, 2)
            new_caches.append(jnp.stack([ck, cv], 0))
            pos = jnp.arange(T_MAX)[None, :]
            row = jnp.arange(s)[:, None]
            mask = jnp.where(pos <= (t + row), 0.0, -1e9)[None, None]
            lg = jnp.einsum("bqhd,bhkd->bhqk", q.astype(jnp.float32),
                            ck.astype(jnp.float32))
            lg = lg / np.sqrt(D // H) + mask
            att = jax.nn.softmax(lg, -1).astype(v.dtype)
            o = jnp.einsum("bhqk,bhkd->bqhd", att, cv).reshape(b, s, D)
            o = qmm(o.reshape(b * s, D),
                    qw["linear_weights"][i]).reshape(b, s, D)
            out = res + (o + weights["linear_biases"][i]).astype(dt)
            res = out
            h = _ln(out, weights["ffn_ln_scales"][i],
                    weights["ffn_ln_biases"][i])
            h = qmm(h.reshape(b * s, D),
                    qw["ffn1_weights"][i]).reshape(b, s, FF)
            h = jax.nn.gelu(h + weights["ffn1_biases"][i])
            h = qmm(h.reshape(b * s, FF),
                    qw["ffn2_weights"][i]).reshape(b, s, D)
            out = res + (h + weights["ffn2_biases"][i]).astype(dt)
        return out, new_caches

    def _ln(x, g, b_):
        m = x.mean(-1, keepdims=True).astype(jnp.float32)
        v = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
        return ((x - m) * jax.lax.rsqrt(v + 1e-5)).astype(x.dtype) * g + b_

    jit_q = jax.jit(int8_step, donate_argnums=(1,))
    caches = [jnp.zeros((2, B, H, T_MAX, D // H), dt) for _ in range(L)]
    out, caches = jit_q(x_pre, caches, jnp.int32(0))
    out.block_until_ready()
    out, caches = jit_q(x_dec, caches, jnp.int32(T_PRE))
    out.block_until_ready()
    t0 = time.perf_counter()
    for i in range(1, steps):
        out, caches = jit_q(x_dec, caches, jnp.int32(T_PRE + i))
    out.block_until_ready()
    dt_q = time.perf_counter() - t0
    toks_q = B * (steps - 1) / dt_q
    print(f"int8 decode: {toks_q:,.0f} tok/s "
          f"({dt_q / (steps - 1) * 1000:.2f} ms/step, "
          f"{toks_q / toks:.2f}x bf16)", flush=True)


if __name__ == "__main__":
    steps = 128
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    main(steps)
