"""Optimistic admission vs full-extent reservation (ISSUE 8): effective
concurrency at a FIXED KV pool size.

``admission="reserve"`` charges every request its worst case up front —
prompt + max_new_tokens — so a pool of P pages serves at most
``P // ceil((prompt + budget) / page_size)`` concurrent requests, even
though most requests stop at eos long before their budget.
``admission="optimistic"`` reserves only prompt + headroom, grows
page-by-page as decode actually proceeds, and preempts (bit-exactly,
prefix-cache-assisted) when the gamble loses. This bench drives the
SAME eos-heavy workload through both modes at the same pool size and
reports:

- effective concurrency — COMPLETED output tokens per decode tick
  (replayed preemption work earns no credit, so thrash cannot inflate
  the number), plus mean active slots per tick,
- drain wall (StubModel replicas: host scheduling cost, not FLOPs),
- the optimistic counters: preemptions, preempt resumes, pages grown
  on demand, headroom reserved,
- the GOODPUT ratio per mode (ISSUE 11 ledger: useful / total device
  tokens) with the replay-waste column — the tokens preemption burns
  re-decoding from token 0 (the PR-8 known cut) are now a measured
  number instead of a footnote,
- the post-drain pool balance (leak check: live == 0 both modes).

The acceptance assert (ISSUE 8) is ``effective_concurrency(optimistic)
>= 1.5 x effective_concurrency(reserve)`` at the default geometry —
the whole point of block-granular paged KV is to stop paying for
tokens that are never generated.

StubModel (tests/_serving_stub.py): closed-form token oracle, no
transformer compiles, and every completed output is verified against
the oracle — a mode that cheated correctness would fail before it
reported a number.

    python benchmarks/preemption_bench.py [--requests N] [--slots N]
        [--pool-pages N] [--prompt-tokens N] [--new-tokens N]
        [--page-size N] [--max-cache-len N] [--eos N] [--headroom N]
"""
import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def _workload(args):
    rng = np.random.default_rng(7)
    return [rng.integers(0, 16, (args.prompt_tokens,)).astype(np.int32)
            for _ in range(args.requests)]


def _oracle(prompt, n, eos):
    from _serving_stub import stub_tokens
    toks = stub_tokens(prompt, n)
    hits = np.nonzero(toks == eos)[0]
    return toks[:int(hits[0]) + 1] if hits.size else toks


def _run_mode(args, admission, prompts):
    from _serving_stub import StubModel
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    from paddle_tpu.telemetry import GoodputLedger
    led = GoodputLedger()
    srv = ContinuousBatchingServer(
        StubModel(), max_slots=args.slots,
        max_cache_len=args.max_cache_len, cache_backend="paged",
        page_size=args.page_size, num_pages=args.pool_pages + 1,
        eos_token_id=args.eos, admission=admission,
        headroom_pages=args.headroom, ledger=led)
    rids = [srv.submit(p, max_new_tokens=args.new_tokens)
            for p in prompts]
    t0 = time.perf_counter()
    ticks = occupied = 0
    while True:
        with srv._lock:
            busy = srv._busy_locked()
        if not busy:
            break
        occupied += srv.step()
        ticks += 1
        assert ticks < 200_000, f"{admission} mode did not converge"
    wall = time.perf_counter() - t0
    outs = srv._results
    total_tokens = 0
    for rid, p in zip(rids, prompts):
        want = _oracle(p, args.new_tokens, args.eos)
        np.testing.assert_array_equal(outs[rid], want)   # bit-exact
        total_tokens += len(want)
    bal = srv.pool_balance()
    assert bal[1] == 0, f"{admission}: leaked {bal[1]} live pages"
    good = led.snapshot()
    return {"mode": admission,
            "requests": len(prompts),
            "tokens": int(total_tokens),
            "ticks": int(ticks),
            "effective_concurrency": total_tokens / max(1, ticks),
            "mean_active": occupied / max(1, ticks),
            "wall_s": wall,
            "preemptions": srv.stats["preemptions"],
            "preempt_resumed": srv.stats["preempt_resumed"],
            "grow_pages": srv.stats["grow_pages"],
            "headroom_pages": srv.stats["headroom_pages"],
            "goodput_ratio": good["goodput_ratio"],
            "replay_tokens": good["tokens"].get("replay", 0),
            "pool": tuple(bal)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--pool-pages", type=int, default=16,
                    help="usable pool pages (the null page is extra)")
    ap.add_argument("--prompt-tokens", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=56,
                    help="per-request budget; eos usually stops decode "
                         "far earlier (the reservation pessimism)")
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-cache-len", type=int, default=64)
    ap.add_argument("--eos", type=int, default=3)
    ap.add_argument("--headroom", type=int, default=1)
    args = ap.parse_args(argv)
    if args.prompt_tokens + args.new_tokens > args.max_cache_len:
        ap.error("prompt + budget must fit max_cache_len")

    prompts = _workload(args)
    modes = [_run_mode(args, "reserve", prompts),
             _run_mode(args, "optimistic", prompts)]
    by = {m["mode"]: m for m in modes}
    ratio = by["optimistic"]["effective_concurrency"] \
        / max(1e-9, by["reserve"]["effective_concurrency"])

    print(f"\npreemption bench: {args.requests} requests, prompt "
          f"{args.prompt_tokens} + budget {args.new_tokens} "
          f"(eos={args.eos} ends most early), pool "
          f"{args.pool_pages} pages x {args.page_size} tok, "
          f"{args.slots} slots")
    hdr = (f"{'mode':<11} {'tok/tick':>9} {'active/tick':>12} "
           f"{'ticks':>6} {'wall ms':>8} {'preempt':>8} "
           f"{'grow pg':>8} {'headroom':>9} {'goodput':>8} "
           f"{'replay tok':>11}")
    print(hdr)
    print("-" * len(hdr))
    for m in modes:
        print(f"{m['mode']:<11} {m['effective_concurrency']:>9.2f} "
              f"{m['mean_active']:>12.2f} {m['ticks']:>6} "
              f"{m['wall_s'] * 1e3:>8.1f} {m['preemptions']:>8} "
              f"{m['grow_pages']:>8} {m['headroom_pages']:>9} "
              f"{m['goodput_ratio']:>8.3f} {m['replay_tokens']:>11}")
    print(f"effective-concurrency ratio (optimistic / reserve): "
          f"{ratio:.2f}x")

    # ISSUE 8 acceptance: the optimism must actually buy concurrency
    # at this fixed pool size (counter-based — wall clock is noise on
    # shared CI)
    assert ratio >= 1.5, (
        f"optimistic admission only reached {ratio:.2f}x effective "
        f"concurrency vs full-extent reservation (expected >= 1.5x)")
    return {"modes": modes, "ratio": ratio, "pool_pages": args.pool_pages}


if __name__ == "__main__":
    main()
