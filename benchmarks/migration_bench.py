"""Drain-with-migration vs evacuate+replay (ISSUE 18): what live
KV-page migration buys at the router's drain path.

Before this PR, draining a replica evacuated its queue and REPLAYED
mid-decode requests from token 0 on a sibling: the sibling re-prefills
the whole prompt and re-decodes every already-emitted token before the
stream advances (deterministic, bit-exact — but pure waste). With live
migration the drain hands off the written pool pages plus resolved
sampler state, and the sibling continues mid-chain: ZERO re-prefill,
zero re-decoded tokens.

This bench drives the SAME seeded workload through both drain modes at
the same fleet geometry and reports, per mode:

- drain-to-last-token wall (StubModel replicas: host scheduling cost,
  not FLOPs),
- the sibling's prefill-token delta across the drain (the re-prefill
  bill; the migration mode SELF-ASSERTS this is exactly 0),
- re-decoded (replayed) tokens — already-emitted tokens the sibling
  must re-decode before producing anything new (evacuate) vs none
  (migrate),
- pages handed off over the migration path,
- pool balance after the dust settles (leak check: live == 0 on both
  replicas, both modes).

Every completed stream is verified bit-exact against the StubModel
closed-form oracle, so a mode that cheated correctness would fail
before it reported a number.

    python benchmarks/migration_bench.py [--requests N] [--slots N]
        [--prompt-tokens N] [--new-tokens N] [--track]
"""
import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))
sys.path.insert(0, os.path.join(_REPO, "scripts"))


def _servers(args):
    from _remote_stub import make_stub_server
    kw = dict(max_slots=args.slots, max_cache_len=args.max_cache_len,
              page_size=args.page_size, num_pages=args.pool_pages)
    return make_stub_server(**kw), make_stub_server(**kw)


def _run_mode(args, mode):
    """One drain drill: submit everything to the source replica, let
    every request stream mid-decode, then drain the source via
    ``mode`` — 'migrate' hands each slot's pages + sampler state to
    the sibling (``migrate_out``/``migrate_in``/``migrate_finish``);
    'evacuate' is the pre-migration story for a replica that must go
    away NOW: drop the slot and replay the request from token 0 on the
    sibling (same resolved seed, so the chain is bit-identical — at
    the price of a full re-prefill plus re-decoding every token the
    source had already emitted). Returns the counters."""
    from _serving_stub import stub_tokens
    from paddle_tpu.reliability import MigrationError

    # both replicas are driven by manual step() from this thread: the
    # drain then lands at an EXACT decode depth, every run — no serve
    # threads racing the gather, no flaky counters
    src, tgt = _servers(args)
    streamed = {}

    def sink(i):
        def cb(_r, toks):
            streamed[i] = streamed.get(i, 0) + len(toks)
        return cb

    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 16,
                            (args.prompt_tokens,)).astype(np.int32)
               for _ in range(args.requests)]
    try:
        rids = [src.submit(p, max_new_tokens=args.new_tokens,
                           seed=100 + i, on_token=sink(i))
                for i, p in enumerate(prompts)]
        # decode every request to an exact mid-stream depth
        drain_at = args.new_tokens // 3
        for _ in range(10_000):
            if all(streamed.get(i, 0) >= drain_at
                   for i in range(args.requests)):
                break
            src.step()
        else:
            raise AssertionError("never reached mid-decode")
        emitted_at_drain = {}
        with src._lock:
            for st in src._slots:
                if st is not None:
                    emitted_at_drain[st.rid] = len(st.emitted)
        pre_prefill = tgt.stats["prefill_tokens"]
        moved = 0
        replayed = 0
        pages = 0
        carried = {}            # submission index -> rid on the sibling
        t0 = time.perf_counter()
        for i, rid in enumerate(rids):
            if mode == "migrate":
                try:
                    state, payloads = src.migrate_out(rid)
                except MigrationError:
                    continue     # finished at home while its siblings
                #                  were being gathered: nothing to move
                if str(state.get("phase")) == "prefill":
                    # mid-prefill slots became migratable with the
                    # prefill->decode handoff (ISSUE 20); this bench
                    # prices mid-DECODE drains only, so resume it at
                    # home rather than skewing the replay accounting
                    src.migrate_abort(rid)
                    print(f"  note: request {i} still mid-prefill at "
                          f"the drain point; skipped "
                          f"(disagg_bench prices the prefill handoff)")
                    continue
                carried[i] = tgt.migrate_in(state, payloads,
                                            on_token=sink(i))
                src.migrate_finish(rid)
                pages += len(payloads)
            else:
                if not src.cancel(rid):
                    continue     # finished at home before the drain
                #                  reached it
                replayed += emitted_at_drain.get(rid, 0)
                carried[i] = tgt.submit(
                    prompts[i], max_new_tokens=args.new_tokens,
                    seed=100 + i, on_token=sink(i))
            moved += 1
        for _ in range(100_000):
            if tgt.in_flight() == 0 and not tgt._queue:
                break
            tgt.step()
        else:
            raise AssertionError("sibling never drained")
        results = {i: tgt.wait(r, timeout=5)
                   for i, r in carried.items()}
        wall = time.perf_counter() - t0
        # bit-exact against the oracle — seeds were fixed at submit,
        # so both drain modes must land the identical stream
        for i, out in results.items():
            np.testing.assert_array_equal(
                out, stub_tokens(prompts[i], args.new_tokens))
        reprefill = tgt.stats["prefill_tokens"] - pre_prefill
        assert moved == args.requests, \
            f"drain caught too few mid-decode: {moved}/{args.requests}"
        if mode == "migrate":
            # the acceptance contract, asserted on every run: a drain
            # that migrates pays ZERO re-prefill on the sibling
            assert reprefill == 0, \
                f"migration re-prefilled {reprefill} tokens"
            assert tgt.stats["admissions"] == 0
            assert tgt.stats["migrated_in"] == moved
            assert src.stats["migrations"] == moved
        for s, name in ((src, "src"), (tgt, "tgt")):
            bal = s.pool_balance()
            assert bal[1] == 0, f"{mode}/{name} leaked: {tuple(bal)}"
        return {"mode": mode, "moved": moved, "wall_s": wall,
                "reprefill_tokens": int(reprefill),
                "replayed_tokens": int(replayed),
                "pages_migrated": int(pages)}
    finally:
        src.stop()
        tgt.stop()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-tokens", type=int, default=11)
    ap.add_argument("--new-tokens", type=int, default=48)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-cache-len", type=int, default=64)
    ap.add_argument("--pool-pages", type=int, default=64)
    ap.add_argument("--track", action="store_true",
                    help="append migration drain rounds to "
                         "BENCHLOG.jsonl")
    args = ap.parse_args(argv)
    if args.prompt_tokens + args.new_tokens > args.max_cache_len:
        ap.error("prompt + budget must fit max_cache_len")

    modes = [_run_mode(args, "migrate"), _run_mode(args, "evacuate")]
    by = {m["mode"]: m for m in modes}
    avoided = by["evacuate"]["reprefill_tokens"] \
        + by["evacuate"]["replayed_tokens"]

    print(f"\nmigration bench: {args.requests} requests, prompt "
          f"{args.prompt_tokens} + budget {args.new_tokens}, "
          f"2 replicas x {args.slots} slots, drain replica 0 "
          f"mid-decode")
    hdr = (f"{'drain mode':<10} {'moved':>6} {'wall ms':>8} "
           f"{'re-prefill tok':>15} {'re-decoded tok':>15} "
           f"{'pages moved':>12}")
    print(hdr)
    print("-" * len(hdr))
    for m in modes:
        print(f"{m['mode']:<10} {m['moved']:>6} "
              f"{m['wall_s'] * 1e3:>8.1f} "
              f"{m['reprefill_tokens']:>15} "
              f"{m['replayed_tokens']:>15} {m['pages_migrated']:>12}")
    print(f"wasted work avoided by migrating: {avoided} tokens "
          f"(re-prefill + replay the evacuate drain pays)")

    if args.track:
        import bench_track
        r = bench_track.append_round(
            {"metric": "migration_drain_target_prefill_tokens",
             "value": by["migrate"]["reprefill_tokens"],
             "unit": "tokens",
             "note": f"{by['migrate']['moved']} mid-decode requests "
                     f"migrated on drain, "
                     f"{by['migrate']['pages_migrated']} pages handed "
                     f"off; the migration path must keep this at "
                     f"exactly 0"})
        print(f"tracked {r['metric']} = {r['value']}")
        r2 = bench_track.append_round(
            {"metric": "migration_drain_replay_tokens_avoided",
             "value": avoided, "unit": "tokens",
             "note": f"re-prefill + re-decode the evacuate+replay "
                     f"drain paid for {by['evacuate']['moved']} "
                     f"mid-decode requests at the same geometry"})
        print(f"tracked {r2['metric']} = {r2['value']}")
    return {"modes": modes, "avoided": avoided}


if __name__ == "__main__":
    main()
