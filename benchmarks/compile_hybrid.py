"""AOT-compile the combined TP×PP×ZeRO hybrid train step (VERDICT r3 #2).

Proves the exact composition the 70B north star needs — mp, pp, sharding
(and dp via the batch axes) in ONE jitted program — lowers and compiles
at real model shapes, without materializing weights:

  - Llama-7B-shaped   tp2 × pp2 × zero1 on 8 virtual devices
  - Llama-70B-shaped  tp4 × pp4 × zero1 (sharding2) on 32 virtual devices

Reference: fleet.distributed_model 4-D hybrid
(python/paddle/distributed/fleet/fleet.py:385-428, base/topology.py:251).

    python benchmarks/compile_hybrid.py [7b|70b|all]
"""
import os
import re
import sys
import time


_BASE = dict(dp=1, sharding=1, sp=1, kv_heads=None, experts=0, top_k=2)
CONFIGS = {
    "7b": dict(_BASE, L=32, H=4096, F=11008, V=32000, NH=32,
               pp=2, sharding=2, mp=2, B=8, S=512, M=4),
    # real Llama-2-70B: GQA with 8 kv heads; flash attention + RoPE
    "70b": dict(_BASE, L=80, H=8192, F=28672, V=32000, NH=64, kv_heads=8,
                pp=4, sharding=2, mp=4, B=16, S=512, M=8),
    # long-context: 7B at seq 32768 with ring attention over sp=2
    # composed with tp2 x pp2 in the same program (SURVEY north star)
    "7b-32k": dict(_BASE, L=32, H=4096, F=11008, V=32000, NH=32,
                   pp=2, mp=2, sp=2, B=2, S=32768, M=2),
    # Mixtral-8x7B-shaped MoE: 8 experts top-2, EP over the mp axis
    "8x7b": dict(_BASE, L=32, H=4096, F=14336, V=32000, NH=32,
                 kv_heads=8, experts=8, pp=2, sharding=2, mp=2, B=8,
                 S=512, M=4),
}


def run(name):
    c = CONFIGS[name]
    L, H, F, V, NH = c["L"], c["H"], c["F"], c["V"], c["NH"]
    NKV = c["kv_heads"] or NH
    dp, pp, sharding, mp, sp = (c["dp"], c["pp"], c["sharding"], c["mp"],
                                c["sp"])
    B, S, M, E = c["B"], c["S"], c["M"], c["experts"]
    n_devices = dp * pp * sharding * mp * sp

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.parallel as dist
    from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                            make_llama_tp_fns,
                                            make_moe_tp_fns)

    mesh = dist.init_mesh(dp=dp, pp=pp, sharding=sharding, mp=mp, sp=sp,
                          devices=jax.devices()[:n_devices])
    kw = dict(n_kv_heads=NKV, use_flash=True, rope_theta=10000.0,
              sp_axis="sp" if sp > 1 else None, sp_degree=sp)
    if E:
        fns, specs = make_moe_tp_fns(NH, mp, num_experts=E,
                                     top_k=c["top_k"], **kw)
    else:
        fns, specs = make_llama_tp_fns(NH, mp, **kw)

    KV = H // NH * NKV
    sds = jax.ShapeDtypeStruct
    blk = {"ln1": sds((H,), jnp.bfloat16), "ln2": sds((H,), jnp.bfloat16),
           "wq": sds((H, H), jnp.bfloat16), "wk": sds((H, KV), jnp.bfloat16),
           "wv": sds((H, KV), jnp.bfloat16), "wo": sds((H, H), jnp.bfloat16)}
    if E:
        blk.update({"w_gate": sds((H, E), jnp.bfloat16),
                    "we_g": sds((E, H, F), jnp.bfloat16),
                    "we_u": sds((E, H, F), jnp.bfloat16),
                    "we_d": sds((E, F, H), jnp.bfloat16)})
        ffn_params = E * 3 * H * F + H * E
    else:
        blk.update({"wg": sds((H, F), jnp.bfloat16),
                    "wu": sds((H, F), jnp.bfloat16),
                    "wd": sds((F, H), jnp.bfloat16)})
        ffn_params = 3 * H * F
    blocks = [blk] * L
    embed = {"table": sds((V, H), jnp.bfloat16)}
    head = {"wo": sds((H, V), jnp.bfloat16)}
    n_params = (L * (2 * H + 2 * H * H + 2 * H * KV + ffn_params)
                + 2 * V * H)
    print(f"[{name}] {n_params/1e9:.2f}B params, mesh dp={dp} pp={pp} "
          f"sharding={sharding} mp={mp} sp={sp} seq={S} "
          f"({n_devices} devices)", flush=True)

    opt = pt.optimizer.AdamW(learning_rate=1e-4)
    t0 = time.perf_counter()
    step_fn, params, opt_state, (p_sh, s_sh) = build_hybrid_train_step(
        *fns, blocks, embed, head, mesh, opt, num_micro=M,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=1,
        seq_axis="sp" if sp > 1 else None)
    t_build = time.perf_counter() - t0

    ids = sds((B, S), jnp.int32)
    step_i = sds((), jnp.int32)
    lr = sds((), jnp.float32)
    t0 = time.perf_counter()
    lowered = step_fn._jit.lower(params, opt_state, ids, ids, step_i, lr)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_comp = time.perf_counter() - t0
    print(f"[{name}] build {t_build:.1f}s, lower {t_lower:.1f}s, "
          f"compile {t_comp:.1f}s", flush=True)
    try:
        mem = compiled.memory_analysis()
        print(f"[{name}] per-device arguments "
              f"{mem.argument_size_in_bytes/1e9:.2f} GB, "
              f"temp {mem.temp_size_in_bytes/1e9:.2f} GB", flush=True)
    except Exception:
        pass
    if sharding > 1:
        assert "sharding" in str(s_sh["m"]["blocks"]["wq"].spec), \
            "ZeRO-1: moments must shard over 'sharding'"
    tag = f"tp{mp}×pp{pp}×zero1" + (f"×sp{sp}" if sp > 1 else "") \
        + (f"×ep{mp}({E}experts)" if E else "")
    print(f"[{name}] hybrid {tag} compile-check OK", flush=True)


def main(which="all"):
    names = list(CONFIGS) if which == "all" else [which]
    n_max = max(CONFIGS[n]["dp"] * CONFIGS[n]["pp"]
                * CONFIGS[n]["sharding"] * CONFIGS[n]["mp"]
                * CONFIGS[n]["sp"] for n in names)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_max}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    for n in names:
        run(n)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "all")
