"""AOT-compile the combined TP×PP×ZeRO hybrid train step (VERDICT r3 #2).

Proves the exact composition the 70B north star needs — mp, pp, sharding
(and dp via the batch axes) in ONE jitted program — lowers and compiles
at real model shapes, without materializing weights:

  - Llama-7B-shaped   tp2 × pp2 × zero1 on 8 virtual devices
  - Llama-70B-shaped  tp4 × pp4 × zero1 (sharding2) on 32 virtual devices

Reference: fleet.distributed_model 4-D hybrid
(python/paddle/distributed/fleet/fleet.py:385-428, base/topology.py:251).

    python benchmarks/compile_hybrid.py [7b|70b|all]
"""
import os
import re
import sys
import time


CONFIGS = {
    # name: (layers, hidden, ffn, vocab, heads, kv_heads, dp, pp,
    #        sharding, mp, sp, batch, seq, micro)
    "7b": (32, 4096, 11008, 32000, 32, 32, 1, 2, 2, 2, 1, 8, 512, 4),
    # real Llama-2-70B: GQA with 8 kv heads; flash attention + RoPE
    "70b": (80, 8192, 28672, 32000, 64, 8, 1, 4, 2, 4, 1, 16, 512, 8),
    # long-context: 7B at seq 32768 with ring attention over sp=2
    # composed with tp2 x pp2 in the same program (SURVEY north star)
    "7b-32k": (32, 4096, 11008, 32000, 32, 32, 1, 2, 1, 2, 2, 2, 32768,
               2),
}


def run(name):
    (L, H, F, V, NH, NKV, dp, pp, sharding, mp, sp, B, S, M) = \
        CONFIGS[name]
    n_devices = dp * pp * sharding * mp * sp

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.parallel as dist
    from paddle_tpu.parallel.hybrid import (build_hybrid_train_step,
                                            make_llama_tp_fns)

    mesh = dist.init_mesh(dp=dp, pp=pp, sharding=sharding, mp=mp, sp=sp,
                          devices=jax.devices()[:n_devices])
    fns, specs = make_llama_tp_fns(
        NH, mp, n_kv_heads=NKV, use_flash=True, rope_theta=10000.0,
        sp_axis="sp" if sp > 1 else None, sp_degree=sp)

    KV = H // NH * NKV
    sds = jax.ShapeDtypeStruct
    blk = {"ln1": sds((H,), jnp.bfloat16), "ln2": sds((H,), jnp.bfloat16),
           "wq": sds((H, H), jnp.bfloat16), "wk": sds((H, KV), jnp.bfloat16),
           "wv": sds((H, KV), jnp.bfloat16), "wo": sds((H, H), jnp.bfloat16),
           "wg": sds((H, F), jnp.bfloat16), "wu": sds((H, F), jnp.bfloat16),
           "wd": sds((F, H), jnp.bfloat16)}
    blocks = [blk] * L
    embed = {"table": sds((V, H), jnp.bfloat16)}
    head = {"wo": sds((H, V), jnp.bfloat16)}
    n_params = (L * (2 * H + 2 * H * H + 2 * H * KV + 3 * H * F)
                + 2 * V * H)
    print(f"[{name}] {n_params/1e9:.2f}B params, mesh dp={dp} pp={pp} "
          f"sharding={sharding} mp={mp} sp={sp} seq={S} "
          f"({n_devices} devices)", flush=True)

    opt = pt.optimizer.AdamW(learning_rate=1e-4)
    t0 = time.perf_counter()
    step_fn, params, opt_state, (p_sh, s_sh) = build_hybrid_train_step(
        *fns, blocks, embed, head, mesh, opt, num_micro=M,
        block_param_specs=specs[0], embed_param_specs=specs[1],
        head_param_specs=specs[2], zero_stage=1,
        seq_axis="sp" if sp > 1 else None)
    t_build = time.perf_counter() - t0

    ids = sds((B, S), jnp.int32)
    step_i = sds((), jnp.int32)
    lr = sds((), jnp.float32)
    t0 = time.perf_counter()
    lowered = step_fn._jit.lower(params, opt_state, ids, ids, step_i, lr)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_comp = time.perf_counter() - t0
    print(f"[{name}] build {t_build:.1f}s, lower {t_lower:.1f}s, "
          f"compile {t_comp:.1f}s", flush=True)
    try:
        mem = compiled.memory_analysis()
        print(f"[{name}] per-device arguments "
              f"{mem.argument_size_in_bytes/1e9:.2f} GB, "
              f"temp {mem.temp_size_in_bytes/1e9:.2f} GB", flush=True)
    except Exception:
        pass
    if sharding > 1:
        assert "sharding" in str(s_sh["m"]["blocks"]["wq"].spec), \
            "ZeRO-1: moments must shard over 'sharding'"
    tag = f"tp{mp}×pp{pp}×zero1" + (f"×sp{sp}" if sp > 1 else "")
    print(f"[{name}] hybrid {tag} compile-check OK", flush=True)


def main(which="all"):
    names = list(CONFIGS) if which == "all" else [which]
    n_max = max(CONFIGS[n][6] * CONFIGS[n][7] * CONFIGS[n][8]
                * CONFIGS[n][9] * CONFIGS[n][10] for n in names)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_max}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    for n in names:
        run(n)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "all")
