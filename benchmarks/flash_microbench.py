"""Microbench: Pallas flash attention (fwd+bwd) vs plain XLA attention on
the real TPU chip. Emits a markdown table (stdout) for BENCHNOTES.md.

Run WITHOUT JAX_PLATFORMS=cpu so the axon TPU is used, and WITHOUT
PYTHONPATH (setting it — to anything — breaks axon plugin discovery; the
repo root is injected below instead).
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as fa


def _fetch(out):
    # np.asarray forces a real host transfer — block_until_ready alone is
    # unreliable under the axon remote-execution relay (see bench.py)
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def bench(fn, *args, iters=20):
    _fetch(fn(*args))   # compile
    _fetch(fn(*args))   # steady-state warmup
    t0 = time.perf_counter()
    for _ in range(iters - 1):
        fn(*args)
    _fetch(fn(*args))
    return (time.perf_counter() - t0) / iters


def _devices_with_retry(attempts=8):
    import os
    last = None
    for i in range(attempts):
        try:
            devs = jax.devices()
            if devs:
                return devs
        except RuntimeError as e:
            last = e
            if "not in the list of known backends" in str(e):
                # plugin discovery failed at import: permanent for this
                # process — re-exec to retry registration from scratch
                n = int(os.environ.get("PT_BENCH_REEXEC", "0"))
                if n < 5:
                    os.environ["PT_BENCH_REEXEC"] = str(n + 1)
                    time.sleep(min(2 ** n * 5, 60))
                    os.execv(sys.executable, [sys.executable] + sys.argv)
                raise
            time.sleep(min(2 ** i, 30))
    raise last if last else RuntimeError("no jax devices")


def main():
    dev = _devices_with_retry()[0]
    print(f"device: {dev.device_kind}", file=sys.stderr)
    b, h, d = 4, 16, 128
    causal = True
    rows = []
    for s in (1024, 2048, 4096):
        rng = np.random.RandomState(0)
        mk = lambda: jax.device_put(jnp.asarray(
            rng.randn(b, h, s, d).astype(np.float32) * 0.3,
            dtype=jnp.bfloat16), dev)
        q, k, v = mk(), mk(), mk()
        sm = 1.0 / np.sqrt(d)

        def pallas_step(q, k, v):
            def loss(q, k, v):
                return fa._flash(q, k, v, sm, causal).astype(
                    jnp.float32).sum()
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

        def xla_step(q, k, v):
            def loss(q, k, v):
                return fa._ref_attention(q, k, v, sm, causal).astype(
                    jnp.float32).sum()
            return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

        def pallas_fwd(q, k, v):
            return fa._flash(q, k, v, sm, causal)

        def xla_fwd(q, k, v):
            return fa._ref_attention(q, k, v, sm, causal)

        t_pf = bench(jax.jit(pallas_fwd), q, k, v)
        t_xf = bench(jax.jit(xla_fwd), q, k, v)
        t_ps = bench(jax.jit(pallas_step), q, k, v)
        t_xs = bench(jax.jit(xla_step), q, k, v)

        # causal attention FLOPs: fwd 2 matmuls = 4*b*h*s^2*d * 0.5;
        # bwd 5 matmuls = 10*b*h*s^2*d * 0.5
        f_fwd = 2.0 * b * h * s * s * d
        f_tot = 7.0 * b * h * s * s * d
        rows.append((s,
                     t_pf * 1e3, f_fwd / t_pf / 1e12,
                     t_xf * 1e3, f_fwd / t_xf / 1e12,
                     t_ps * 1e3, f_tot / t_ps / 1e12,
                     t_xs * 1e3, f_tot / t_xs / 1e12))
        print(f"seq={s} done", file=sys.stderr)

    print(f"\nShapes b={b} h={h} d={d} bf16 causal; device {dev.device_kind}")
    print("| seq | pallas fwd ms (TF/s) | xla fwd ms (TF/s) | "
          "pallas fwd+bwd ms (TF/s) | xla fwd+bwd ms (TF/s) |")
    print("|---|---|---|---|---|")
    for s, pf, pft, xf, xft, ps, pst, xs, xst in rows:
        print(f"| {s} | {pf:.2f} ({pft:.1f}) | {xf:.2f} ({xft:.1f}) | "
              f"{ps:.2f} ({pst:.1f}) | {xs:.2f} ({xst:.1f}) |")


if __name__ == "__main__":
    main()
