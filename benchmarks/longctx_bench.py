"""Long-context attention on the real chip (VERDICT r3 #5): Pallas flash
fwd+bwd vs the XLA reference composition at seq 8k-32k, single chip.

The multi-device ring/Ulysses paths are validated on the virtual CPU mesh
(tests/test_moe_ring.py, dryrun sp section); with ONE physical chip the
per-chip flash kernel is the measurable long-context component — its
advantage compounds under ring attention (each ring step runs this kernel
on a [S_local x S_local] block).

    python benchmarks/longctx_bench.py [--seqs 8192,16384,32768]
"""
import sys
import time

import numpy as np


def bench_one(seq, with_ref):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import (_flash,
                                                       _ref_attention)

    B, H, D = 1, 16, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, seq, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, H, seq, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, H, seq, D)), jnp.bfloat16)
    sm = 1.0 / np.sqrt(D)

    def train(fn):
        def f(q, k, v):
            return fn(q, k, v).astype(jnp.float32).sum()

        g = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))
        out = g(q, k, v)          # compile + warm
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            out = g(q, k, v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    t_flash = train(lambda q, k, v: _flash(q, k, v, sm, True))
    # causal attention FLOPs: fwd 2*2*B*H*S^2/2*D, bwd ~2.5x fwd
    flops = 3.5 * 2 * B * H * seq * seq * D
    print(f"seq {seq}: flash fwd+bwd {t_flash * 1000:.1f} ms "
          f"({flops / t_flash / 1e12:.1f} TF/s eff)", flush=True)
    if with_ref:
        t_ref = train(lambda q, k, v: _ref_attention(q, k, v, sm, True))
        print(f"seq {seq}: XLA ref fwd+bwd {t_ref * 1000:.1f} ms -> "
              f"flash {t_ref / t_flash:.2f}x", flush=True)


def main(seqs):
    for s in seqs:
        # the O(S^2)-memory reference OOMs/thrashes at 32k on one v5e
        bench_one(s, with_ref=s <= 16384)


if __name__ == "__main__":
    seqs = [8192, 16384, 32768]
    if "--seqs" in sys.argv:
        seqs = [int(x) for x in
                sys.argv[sys.argv.index("--seqs") + 1].split(",")]
    main(seqs)
