"""Multi-replica router bench (ISSUE 7): cache-aware routing vs
round-robin, failover requeue latency, and rolling-restart drain wall.

Drives a shared-prefix workload — G system prompts, each followed by a
short random tail — through ``ReplicaRouter`` in three configurations:

- ``1-replica``   the single-server baseline (every request lands on
  the only pool, so its prefix cache sees everything),
- ``rr-N``        N replicas, ``policy="round_robin"`` — the
  affinity-blind baseline: same-prefix traffic sprays across pools and
  each replica must cache every group separately,
- ``affinity-N``  N replicas, ``policy="affinity"`` — sketch-routed:
  same-prefix traffic sticks to the replica already holding its pages,

and reports per mode:

- RAW prefix hit rate (replica ``prefix_auto_hits`` counters over all
  requests) next to the COLD-MISS COUNT — the structural misses each
  policy pays: 1-replica/affinity miss once per group, round-robin
  once per (replica, group) pair its rotation touches; the cold column
  IS the affinity story at a glance,
- prefill tokens actually computed (the counter that generalizes:
  affinity should approach the 1-replica number at N-replica
  throughput),
- drain wall for the whole workload (submitted round-by-round —
  steady traffic, not one burst; StubModel replicas, so this is
  HOST-side routing + serving cost, not model FLOPs).

Then two robustness numbers on the affinity fleet:

- failover requeue latency: K requests queued on a victim replica,
  ``kill()``, one supervisor ``poll()`` — the wall covers harvest +
  re-dispatch of all K (per-request latency printed), results verified
  bit-exact on the siblings,
- rolling-restart drain wall: ``rolling_restart()`` across the fleet
  mid-workload, asserted zero failed requests.

StubModel replicas (tests/_serving_stub.py) keep the bench about the
ROUTER: no transformer compiles, closed-form token oracle, tier-1-fast.
Counters are the signal; walls on shared CI are noise-prone.

    python benchmarks/router_bench.py [--requests-per-group N]
        [--groups N] [--replicas N] [--system-tokens N]
        [--tail-tokens N] [--new-tokens N] [--slots N] [--failover-k N]
"""
import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def _build(args, n, policy):
    from _serving_stub import StubModel
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    from paddle_tpu.inference.router import ReplicaRouter
    reps = [ContinuousBatchingServer(
        StubModel(), max_slots=args.slots,
        max_cache_len=args.max_cache_len, cache_backend="paged",
        page_size=args.page_size) for _ in range(n)]
    return ReplicaRouter(reps, policy=policy), reps


def _build_remote(args, n, policy):
    """N replicas as SPAWNED PROCESSES behind the wire transport
    (ISSUE 12) — same server config, reached through RemoteReplica
    proxies. Returns (router, reps, procs); callers must _teardown."""
    from paddle_tpu.inference.remote import (RemoteReplica,
                                             spawn_replica_host)
    from paddle_tpu.inference.router import ReplicaRouter
    from _remote_stub import make_stub_server
    kw = {"max_slots": args.slots, "max_cache_len": args.max_cache_len,
          "page_size": args.page_size}
    procs, reps = [], []
    for _ in range(n):
        proc, addr = spawn_replica_host(make_stub_server, kw,
                                        heartbeat_s=0.02,
                                        start_server=True)
        procs.append(proc)
        reps.append(RemoteReplica(addr, call_timeout_s=10.0))
    return ReplicaRouter(reps, policy=policy), reps, procs


def _teardown_remote(reps, procs):
    for rep in reps:
        try:
            rep.shutdown()
        except Exception:
            pass                     # already dead: fine for teardown
    for proc in procs:
        proc.join(10)
        if proc.is_alive():
            proc.kill()


def _workload(args):
    rng = np.random.default_rng(0)
    groups = [rng.integers(0, 16, (args.system_tokens,)).astype(np.int32)
              for _ in range(args.groups)]
    rounds = []
    for _ in range(args.requests_per_group):
        # shuffled group order per round: real traffic does not arrive
        # in a fixed rotation (a fixed order congruent with the replica
        # count would hand round-robin accidental perfect affinity)
        order = rng.permutation(args.groups)
        rounds.append([np.concatenate(
            [groups[g], rng.integers(0, 16, (args.tail_tokens,))
             .astype(np.int32)]) for g in order])
    return rounds


def _run_mode(args, rounds, n, policy, remote=False):
    from _serving_stub import stub_tokens
    procs = None
    if remote:
        router, reps, procs = _build_remote(args, n, policy)
    else:
        router, reps = _build(args, n, policy)
    router.start(poll_interval=0.005)
    n_req = sum(len(r) for r in rounds)
    paced = 0.0
    t0 = time.perf_counter()
    for rnd in rounds:                      # steady traffic: one round
        rids = [(router.submit(p, max_new_tokens=args.new_tokens), p)
                for p in rnd]               # in flight at a time
        for rid, p in rids:
            got = router.wait(rid, timeout=120)
            np.testing.assert_array_equal(
                got, stub_tokens(p, args.new_tokens))
        if remote:
            # steady-traffic pacing: let the round's donations reach
            # the pushed sketches before the next round routes (the
            # digest cadence is what an in-process fleet gets for
            # free). Pacing is idle time between rounds, so it is
            # SUBTRACTED from the reported wall.
            time.sleep(0.06)
            paced += 0.06
    wall = time.perf_counter() - t0 - paced
    if remote:
        time.sleep(0.1)                     # final digest refresh
    hits = sum(r.stats["prefix_auto_hits"] for r in reps)
    prefill = sum(r.stats["prefill_tokens"] for r in reps)
    router.stop()
    if procs is not None:
        _teardown_remote(reps, procs)
    # cold misses = admissions that found no cached prefix anywhere in
    # the fleet: 1-replica/affinity pay one per GROUP, round-robin one
    # per (replica, group) pair its rotation touches — the spread is
    # exactly the locality the affinity policy exists to keep
    tag = f"{policy}-{n}" if n > 1 else "1-replica"
    return {"mode": tag + ("-remote" if remote else ""),
            "hit_rate": hits / n_req, "cold_misses": n_req - hits,
            "hits": hits, "prefill_tokens": prefill,
            "affinity_hits": router.stats["affinity_hits"],
            "wall_s": wall}


def _build_tiered(args, n, policy):
    """N replicas with a SQUEEZED pool (7 pages) and a host tier each
    — the ISSUE 17 session fleet: a user's turn-1 history cannot stay
    HBM-resident, so the affinity signal the router reads MUST cover
    host-resident runs (``PrefixCache.sketch()`` keeps spilled
    fingerprints) or returning sessions route blind."""
    from _serving_stub import StubModel
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    from paddle_tpu.inference.kv_tier import HostTier
    from paddle_tpu.inference.router import ReplicaRouter
    reps = [ContinuousBatchingServer(
        StubModel(), max_slots=args.slots,
        max_cache_len=args.max_cache_len, cache_backend="paged",
        page_size=args.page_size, num_pages=7,
        host_tier=HostTier()) for _ in range(n)]
    return ReplicaRouter(reps, policy=policy), reps


def _bench_sessions(args):
    """Session-affinity column (ISSUE 17): U users each serve a
    distinct 2-page first turn across the tiered fleet, then every
    user RETURNS with a prompt extending their own history. Reported
    per policy: turn-2 prefix hit tokens (the rate is hit / ideal),
    pages restored from host, and host residency — round-robin's
    rotation sends the returning turn to a different replica, so its
    history is a cross-replica miss; affinity follows the sketch back
    to the replica still holding it in EITHER tier."""
    from _serving_stub import stub_tokens
    rng = np.random.default_rng(11)
    users = [rng.integers(0, 16, (args.session_tokens,))
             .astype(np.int32) for _ in range(args.session_users)]
    ideal = args.session_users * \
        (args.session_tokens // args.page_size) * args.page_size
    rows = []
    for policy in ("round_robin", "affinity"):
        router, reps = _build_tiered(args, args.replicas, policy)
        rids = [(router.submit(p, max_new_tokens=4), p) for p in users]
        _drain_single(router, reps)
        for rid, p in rids:                 # turn 1: build histories
            np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                          stub_tokens(p, 4))
        h0 = sum(r.stats["prefix_auto_hit_tokens"] for r in reps)
        r0 = sum(r.host_tier.restored_pages_total for r in reps)
        exts = [np.concatenate([p, stub_tokens(p, 4)[:2],
                                np.asarray([int(p[0]) % 16], np.int32)])
                for p in users]
        rids = [(router.submit(e, max_new_tokens=4), e) for e in exts]
        _drain_single(router, reps)
        for rid, e in rids:                 # turn 2: return to them
            np.testing.assert_array_equal(router.wait(rid, timeout=5),
                                          stub_tokens(e, 4))
        hit_tok = sum(r.stats["prefix_auto_hit_tokens"]
                      for r in reps) - h0
        restored = sum(r.host_tier.restored_pages_total
                       for r in reps) - r0
        corrupt = sum(r.host_tier.restore_corrupt_total for r in reps)
        host_pages = sum(r.host_tier.stats()["entries"] for r in reps)
        rows.append({"mode": f"{policy}-{args.replicas}",
                     "turn2_hit_tokens": hit_tok, "ideal": ideal,
                     "hit_rate": hit_tok / max(ideal, 1),
                     "restored": restored, "corrupt": corrupt,
                     "host_pages": host_pages})
    rr, aff = rows
    assert aff["turn2_hit_tokens"] > rr["turn2_hit_tokens"], \
        "session affinity must beat round-robin on returning turns"
    assert aff["restored"] > 0 and aff["corrupt"] == 0
    return rows


def _bench_failover(args):
    """K requests queued on a victim replica; kill it; ONE poll
    harvests + re-dispatches all K. Deterministic single-threaded."""
    from _serving_stub import stub_tokens
    router, reps = _build(args, args.replicas, "affinity")
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 16, (args.system_tokens,)).astype(np.int32)
    seed_p = np.concatenate([shared, np.asarray([1], np.int32)])
    rid = router.submit(seed_p, max_new_tokens=2)
    _drain_single(router, reps)
    router.wait(rid, timeout=5)
    victim = int(np.argmax(router.stats["routed"]))
    qs = []
    for i in range(args.failover_k):
        p = np.concatenate([shared, np.asarray([2, i % 16], np.int32)])
        qs.append((router.submit(p, max_new_tokens=args.new_tokens), p))
    assert reps[victim].queue_depth() == args.failover_k
    reps[victim].kill()
    t0 = time.perf_counter()
    router.poll()                           # harvest + requeue them all
    requeue_wall = time.perf_counter() - t0
    assert router.stats["requeued"] == args.failover_k
    _drain_single(router, reps)
    for r, p in qs:
        np.testing.assert_array_equal(
            router.wait(r, timeout=5),
            stub_tokens(p, args.new_tokens))
    return {"k": args.failover_k, "requeue_wall_s": requeue_wall,
            "per_request_ms": requeue_wall / args.failover_k * 1e3}


def _drain_single(router, reps, max_iters=5000):
    idle = 0
    for _ in range(max_iters):
        router.poll()
        busy = False
        for rep in reps:
            if rep.health == "dead":
                continue
            if rep.queue_depth() or rep.in_flight():
                rep.step()
                busy = True
        idle = 0 if busy else idle + 1
        if idle >= 2:
            return
    raise AssertionError("bench drive did not converge")


def _bench_rolling_restart(args, rounds):
    from _serving_stub import stub_tokens
    router, _ = _build(args, args.replicas, "affinity")
    router.start(poll_interval=0.005)
    rids = [(router.submit(p, max_new_tokens=args.new_tokens), p)
            for rnd in rounds for p in rnd]
    t0 = time.perf_counter()
    router.rolling_restart(drain_timeout=120.0)
    wall = time.perf_counter() - t0
    failed = 0
    for rid, p in rids:
        try:
            np.testing.assert_array_equal(
                router.wait(rid, timeout=120),
                stub_tokens(p, args.new_tokens))
        except Exception:
            failed += 1
    router.stop()
    return {"drain_wall_s": wall, "failed": failed,
            "restarts": router.stats["restarts"],
            "requeued": router.stats["requeued"]}


def _bench_remote(args, rounds):
    """ISSUE 12: the same affinity workload over PROCESS replicas —
    sketch routing from pushed digests, rolling restart of real
    processes, and the per-call wire overhead (ping p50/p99)."""
    from _serving_stub import stub_tokens
    mode = _run_mode(args, rounds, args.replicas, "affinity",
                     remote=True)

    router, reps, procs = _build_remote(args, args.replicas, "affinity")
    try:
        rtts = sorted(reps[0].ping() for _ in range(200))
        p50 = rtts[len(rtts) // 2]
        p99 = rtts[int(len(rtts) * 0.99)]
        router.start(poll_interval=0.005)
        rids = [(router.submit(p, max_new_tokens=args.new_tokens), p)
                for rnd in rounds for p in rnd]
        t0 = time.perf_counter()
        router.rolling_restart(drain_timeout=120.0)
        rr_wall = time.perf_counter() - t0
        failed = 0
        for rid, p in rids:
            try:
                np.testing.assert_array_equal(
                    router.wait(rid, timeout=120),
                    stub_tokens(p, args.new_tokens))
            except Exception:
                failed += 1
        router.stop()
    finally:
        _teardown_remote(reps, procs)
    mode.update({"wire_p50_us": p50 * 1e6, "wire_p99_us": p99 * 1e6,
                 "rr_wall_s": rr_wall, "rr_failed": failed,
                 "rr_restarts": router.stats["restarts"]})
    return mode


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests-per-group", type=int, default=12)
    ap.add_argument("--groups", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--system-tokens", type=int, default=48)
    ap.add_argument("--tail-tokens", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-cache-len", type=int, default=128)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--failover-k", type=int, default=8)
    ap.add_argument("--sessions", action="store_true",
                    help="also run the ISSUE 17 session-affinity "
                         "column: returning users over a TIERED fleet "
                         "(7-page pools + host tier per replica) — "
                         "affinity must follow the sketch back to the "
                         "replica holding the user's spilled history")
    ap.add_argument("--session-users", type=int, default=8)
    ap.add_argument("--session-tokens", type=int, default=16)
    ap.add_argument("--track", action="store_true",
                    help="append the session-affinity round to "
                         "BENCHLOG.jsonl (needs --sessions)")
    ap.add_argument("--remote", action="store_true",
                    help="also run the affinity fleet as spawned "
                         "PROCESS replicas over the wire transport "
                         "(ISSUE 12): hit-rate parity, rolling "
                         "restart of processes, per-call overhead")
    args = ap.parse_args(argv)

    rounds = _workload(args)
    n_req = sum(len(r) for r in rounds)
    print(f"router bench: {n_req} requests "
          f"({args.groups} groups x {args.requests_per_group}), "
          f"{args.replicas} replicas, system={args.system_tokens} "
          f"tail={args.tail_tokens} new={args.new_tokens}")
    modes = [_run_mode(args, rounds, 1, "affinity"),
             _run_mode(args, rounds, args.replicas, "round_robin"),
             _run_mode(args, rounds, args.replicas, "affinity")]
    print(f"\n  {'mode':<14} {'hit_rate':>8} {'cold':>5} "
          f"{'prefill_tok':>11} {'wall_ms':>8}")
    for m in modes:
        print(f"  {m['mode']:<14} {m['hit_rate']:>8.2f} "
              f"{m['cold_misses']:>5} {m['prefill_tokens']:>11} "
              f"{m['wall_s'] * 1e3:>8.1f}")
    fo = _bench_failover(args)
    print(f"\n  failover: {fo['k']} queued requests requeued in "
          f"{fo['requeue_wall_s'] * 1e3:.2f} ms "
          f"({fo['per_request_ms']:.3f} ms/req), siblings bit-exact")
    rr = _bench_rolling_restart(args, rounds)
    print(f"  rolling restart: {rr['restarts']} replicas bounced in "
          f"{rr['drain_wall_s'] * 1e3:.1f} ms under load, "
          f"{rr['failed']} failed requests, "
          f"{rr['requeued']} requeued")
    out = {"modes": modes, "failover": fo, "rolling_restart": rr}
    if args.sessions:
        rows = _bench_sessions(args)
        print(f"\n  sessions ({args.session_users} users x 2 turns, "
              f"tiered replicas: 7-page pools + host tier):")
        print(f"  {'mode':<14} {'t2_hit_rate':>11} {'hit_tok':>8} "
              f"{'restored':>8} {'host_pages':>10}")
        for m in rows:
            print(f"  {m['mode']:<14} {m['hit_rate']:>11.2f} "
                  f"{m['turn2_hit_tokens']:>8} {m['restored']:>8} "
                  f"{m['host_pages']:>10}")
        print(f"  returning turns follow the sketch home: affinity "
              f"restores spilled history, round-robin's rotation "
              f"lands on replicas that never saw it")
        out["sessions"] = rows
        if args.track:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "bench_track",
                os.path.join(_REPO, "scripts", "bench_track.py"))
            bench_track = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(bench_track)
            aff = rows[-1]
            r = bench_track.append_round(
                {"metric": "router_session_affinity_hit_rate",
                 "value": aff["hit_rate"], "unit": "ratio",
                 "note": f"{args.session_users} users x 2 turns, "
                         f"{args.replicas} tiered stub replicas "
                         f"(round-robin baseline "
                         f"{rows[0]['hit_rate']:.2f}); "
                         f"{aff['restored']} pages restored"})
            print(f"  tracked {r['metric']} = {r['value']:.2f}")
    if args.remote:
        rm = _bench_remote(args, rounds)
        inproc = modes[-1]               # the in-process affinity fleet
        print(f"\n  remote ({args.replicas} process replicas over the "
              f"wire transport):")
        print(f"    {rm['mode']:<22} hit_rate {rm['hit_rate']:.2f} "
              f"(in-process {inproc['hit_rate']:.2f}, "
              f"delta {rm['hit_rate'] - inproc['hit_rate']:+.3f}), "
              f"wall {rm['wall_s'] * 1e3:.1f} ms")
        print(f"    wire round trip: p50 {rm['wire_p50_us']:.0f} us, "
              f"p99 {rm['wire_p99_us']:.0f} us")
        print(f"    rolling restart of processes: "
              f"{rm['rr_restarts']} bounced in "
              f"{rm['rr_wall_s'] * 1e3:.1f} ms, "
              f"{rm['rr_failed']} failed requests")
        out["remote"] = rm
    return out


if __name__ == "__main__":
    main()
