"""AOT-compile the Llama-2-7B TP(+ZeRO-2) train step on a virtual mesh.

BASELINE.md's 7B row needs a multi-chip slice to *measure*; this proves
the full-size program (real shapes, real TP/sharding layouts) lowers and
compiles — the part that usually breaks (sharding mismatches, layout
OOMs in SPMD partitioning) — without executing a step.

    python benchmarks/compile_7b_tp.py [n_devices]
"""
import os
import sys
import time


def main(n_devices=8):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.parallel as dist
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(tensor_parallel=True)          # 7B defaults
    mesh = dist.init_mesh(mp=4, sharding=2,
                          devices=jax.devices()[:n_devices])

    # Build the model ABSTRACTLY: construct a tiny clone for structure,
    # then rebuild the param tree as ShapeDtypeStructs at 7B shapes by
    # scaling the config — avoids materializing 28 GB of fp32 weights.
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    t0 = time.perf_counter()
    tiny_cfg = llama_tiny(tensor_parallel=True)
    tiny = LlamaForCausalLM(tiny_cfg)

    def scale_shape(name, shape):
        """Map a tiny-model param shape to the 7B shape by dimension
        role (vocab/hidden/intermediate/heads)."""
        m = {tiny_cfg.vocab_size: cfg.vocab_size,
             tiny_cfg.hidden_size: cfg.hidden_size,
             tiny_cfg.intermediate_size: cfg.intermediate_size,
             tiny_cfg.num_heads * tiny_cfg.head_dim:
                 cfg.num_heads * cfg.head_dim,
             tiny_cfg.num_kv_heads * tiny_cfg.head_dim:
                 cfg.num_kv_heads * cfg.head_dim}
        return tuple(m.get(d, d) for d in shape)

    # per-layer names repeat: build layer-0 shapes then replicate
    tiny_params = tiny.raw_params()
    abstract = {}
    for name, v in tiny_params.items():
        if ".layers." in name:
            if ".layers.0." not in name:
                continue
            for i in range(cfg.num_layers):
                n7 = name.replace(".layers.0.", f".layers.{i}.")
                abstract[n7] = jax.ShapeDtypeStruct(
                    scale_shape(name, v.shape), jnp.bfloat16)
        else:
            abstract[name] = jax.ShapeDtypeStruct(
                scale_shape(name, v.shape), jnp.bfloat16)
    n_params = sum(int(np.prod(s.shape)) for s in abstract.values())
    print(f"abstract 7B param tree: {len(abstract)} tensors, "
          f"{n_params/1e9:.2f}B params "
          f"({time.perf_counter()-t0:.1f}s)", flush=True)

    # the REAL 7B model instance for tracing: same structure, but its
    # forward only needs shapes under eval_shape/lower — construct the
    # full-size module lazily per layer is not possible, so trace through
    # the tiny module rebuilt at 7B config WITHOUT init: we override the
    # initializer to zeros-via-eval_shape... simplest robust route: trace
    # a functional forward defined directly over the param dict.
    from paddle_tpu.ops.pallas import rope as rope_mod

    hd = cfg.head_dim
    cos_np, sin_np = rope_mod.precompute_freqs(hd, 512, cfg.rope_theta)
    cos = jnp.asarray(cos_np)
    sin = jnp.asarray(sin_np)

    def fwd(params, ids):
        x = params["model.embed_tokens.weight"][ids]
        for i in range(cfg.num_layers):
            p = lambda s: params[f"model.layers.{i}.{s}"]
            h = _rms(x, p("input_layernorm.weight"))
            b, s_len = ids.shape
            q = (h @ p("self_attn.q_proj.weight")).reshape(
                b, s_len, cfg.num_heads, hd)
            k = (h @ p("self_attn.k_proj.weight")).reshape(
                b, s_len, cfg.num_kv_heads, hd)
            v = (h @ p("self_attn.v_proj.weight")).reshape(
                b, s_len, cfg.num_kv_heads, hd)
            q = rope_mod.apply_rotary(q, cos, sin)
            k = rope_mod.apply_rotary(k, cos, sin)
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            from paddle_tpu.ops.pallas import flash_attention as fa
            att = fa._ref_attention_bshd(q, k, v) if hasattr(
                fa, "_ref_attention_bshd") else _xla_attn(q, k, v)
            att = att.reshape(b, s_len, cfg.num_heads * hd)
            x = x + att @ p("self_attn.o_proj.weight")
            h = _rms(x, p("post_attention_layernorm.weight"))
            g = h @ p("mlp.gate_proj.weight")
            u = h @ p("mlp.up_proj.weight")
            x = x + (jax.nn.silu(g) * u) @ p("mlp.down_proj.weight")
        x = _rms(x, params["model.norm.weight"])
        logits = x @ params["lm_head.weight"]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, ids[:, 1:, None], -1).mean()

    def _rms(x, w):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1,
                       keepdims=True)
        return (x * jax.lax.rsqrt(var + 1e-5)).astype(x.dtype) * w

    def _xla_attn(q, k, v):
        s = q.shape[1]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -1e9)
        p = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def grad_step(params, ids):
        return jax.value_and_grad(fwd)(params, ids)

    # shardings: TP layouts per the fleet mapping + ZeRO over 'sharding'
    from jax.sharding import NamedSharding
    from paddle_tpu.parallel.api import zero_spec
    from paddle_tpu.parallel.mesh import P

    def spec_of(name, shape):
        if "embed_tokens" in name or "lm_head" in name:
            base = P("mp", None) if "embed" in name else P(None, "mp")
        elif any(k in name for k in ("q_proj", "k_proj", "v_proj",
                                     "gate_proj", "up_proj")):
            base = P(None, "mp")
        elif any(k in name for k in ("o_proj", "down_proj")):
            base = P("mp", None)
        else:
            base = P()
        return NamedSharding(mesh.mesh, zero_spec(shape, base, mesh))

    in_shardings = ({n: spec_of(n, s.shape) for n, s in abstract.items()},
                    None)
    ids_abs = jax.ShapeDtypeStruct((8, 512), jnp.int32)

    t0 = time.perf_counter()
    lowered = jax.jit(grad_step, in_shardings=in_shardings).lower(
        abstract, ids_abs)
    t_lower = time.perf_counter() - t0
    print(f"lowered 7B TP4xZeRO2 program in {t_lower:.1f}s", flush=True)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_comp = time.perf_counter() - t0
    mem = compiled.memory_analysis()
    print(f"compiled in {t_comp:.1f}s", flush=True)
    try:
        print(f"  per-device argument bytes: "
              f"{mem.argument_size_in_bytes/1e9:.2f} GB, "
              f"temp: {mem.temp_size_in_bytes/1e9:.2f} GB", flush=True)
    except Exception:
        pass
    print("7B TP compile-check OK")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
