"""AOT-compile the Llama-2-7B TP train step on a virtual mesh, in both
ZeRO layouts (stage 2: params replicated over 'sharding'; stage 3:
params sharded).

BASELINE.md's 7B row needs a multi-chip slice to *measure*; this proves
the full-size program (real shapes, real TP/sharding layouts) lowers and
compiles — the part that usually breaks (sharding mismatches, layout
OOMs in SPMD partitioning) — without executing a step.

    python benchmarks/compile_7b_tp.py [n_devices]
"""
import os
import sys
import time


def main(n_devices=8):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import re
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu.parallel as dist
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(tensor_parallel=True)          # 7B defaults
    mp = min(4, max(1, n_devices // 2)) if n_devices > 1 else 1
    shard_deg = n_devices // mp
    mesh = dist.init_mesh(mp=mp, sharding=shard_deg,
                          devices=jax.devices()[:n_devices])

    # Build the model ABSTRACTLY: construct a tiny clone for structure,
    # then rebuild the param tree as ShapeDtypeStructs at 7B shapes by
    # scaling the config — avoids materializing 28 GB of fp32 weights.
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    t0 = time.perf_counter()
    tiny_cfg = llama_tiny(tensor_parallel=True)
    tiny = LlamaForCausalLM(tiny_cfg)

    def scale_shape(name, shape):
        """Map a tiny-model param shape to the 7B shape by dimension
        role (vocab/hidden/intermediate/heads)."""
        m = {tiny_cfg.vocab_size: cfg.vocab_size,
             tiny_cfg.hidden_size: cfg.hidden_size,
             tiny_cfg.intermediate_size: cfg.intermediate_size,
             tiny_cfg.num_heads * tiny_cfg.head_dim:
                 cfg.num_heads * cfg.head_dim,
             tiny_cfg.num_kv_heads * tiny_cfg.head_dim:
                 cfg.num_kv_heads * cfg.head_dim}
        return tuple(m.get(d, d) for d in shape)

    # per-layer names repeat: build layer-0 shapes then replicate
    tiny_params = tiny.raw_params()
    abstract = {}
    for name, v in tiny_params.items():
        if ".layers." in name:
            if ".layers.0." not in name:
                continue
            for i in range(cfg.num_layers):
                n7 = name.replace(".layers.0.", f".layers.{i}.")
                abstract[n7] = jax.ShapeDtypeStruct(
                    scale_shape(name, v.shape), jnp.bfloat16)
        else:
            abstract[name] = jax.ShapeDtypeStruct(
                scale_shape(name, v.shape), jnp.bfloat16)
    n_params = sum(int(np.prod(s.shape)) for s in abstract.values())
    print(f"abstract 7B param tree: {len(abstract)} tensors, "
          f"{n_params/1e9:.2f}B params "
          f"({time.perf_counter()-t0:.1f}s)", flush=True)

    # Trace a functional forward defined directly over the param dict
    # (constructing a real 7B module would materialize 28 GB of weights).
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas import rope as rope_mod

    hd = cfg.head_dim
    cos_np, sin_np = rope_mod.precompute_freqs(hd, 512, cfg.rope_theta)
    cos = jnp.asarray(cos_np)
    sin = jnp.asarray(sin_np)

    def fwd(params, ids):
        x = params["model.embed_tokens.weight"][ids]
        for i in range(cfg.num_layers):
            p = lambda s: params[f"model.layers.{i}.{s}"]
            h = _rms(x, p("input_layernorm.weight"))
            b, s_len = ids.shape
            q = (h @ p("self_attn.q_proj.weight")).reshape(
                b, s_len, cfg.num_heads, hd)
            k = (h @ p("self_attn.k_proj.weight")).reshape(
                b, s_len, cfg.num_kv_heads, hd)
            v = (h @ p("self_attn.v_proj.weight")).reshape(
                b, s_len, cfg.num_kv_heads, hd)
            q = rope_mod.apply_rotary(q, cos, sin)
            k = rope_mod.apply_rotary(k, cos, sin)
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            # the model's real attention routing (Pallas on TPU; on this
            # CPU mesh it falls back to the reference composition, which
            # materializes scores — the reported temp bytes are an UPPER
            # bound on the TPU program's)
            att = fa.flash_attention(q, k, v, causal=True)
            att = att.reshape(b, s_len, cfg.num_heads * hd)
            x = x + att @ p("self_attn.o_proj.weight")
            h = _rms(x, p("post_attention_layernorm.weight"))
            g = h @ p("mlp.gate_proj.weight")
            u = h @ p("mlp.up_proj.weight")
            x = x + (jax.nn.silu(g) * u) @ p("mlp.down_proj.weight")
        x = _rms(x, params["model.norm.weight"])
        logits = x @ params["lm_head.weight"]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, ids[:, 1:, None], -1).mean()

    def _rms(x, w):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1,
                       keepdims=True)
        return (x * jax.lax.rsqrt(var + 1e-5)).astype(x.dtype) * w

    def grad_step(params, ids):
        return jax.value_and_grad(fwd)(params, ids)

    # shardings: TP layouts per the fleet mapping + ZeRO over 'sharding'
    from jax.sharding import NamedSharding
    from paddle_tpu.parallel.api import zero_spec
    from paddle_tpu.parallel.mesh import P

    def spec_of(name, shape, stage3):
        if "embed_tokens" in name or "lm_head" in name:
            base = P("mp", None) if "embed" in name else P(None, "mp")
        elif any(k in name for k in ("q_proj", "k_proj", "v_proj",
                                     "gate_proj", "up_proj")):
            base = P(None, "mp")
        elif any(k in name for k in ("o_proj", "down_proj")):
            base = P("mp", None)
        else:
            base = P()
        # stage 2: params stay replicated over 'sharding' (only grads/
        # optimizer state shard — parallel/api.py param_shardings);
        # stage 3: params shard over 'sharding' too (zero_spec)
        spec = zero_spec(shape, base, mesh) if stage3 else base
        return NamedSharding(mesh.mesh, spec)

    ids_abs = jax.ShapeDtypeStruct((8, 512), jnp.int32)
    for stage3 in (False, True):
        tag = "ZeRO-3 (params sharded)" if stage3 else             "ZeRO-2 (params replicated over sharding)"
        in_shardings = ({n: spec_of(n, s.shape, stage3)
                         for n, s in abstract.items()}, None)
        t0 = time.perf_counter()
        lowered = jax.jit(grad_step, in_shardings=in_shardings).lower(
            abstract, ids_abs)
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_comp = time.perf_counter() - t0
        print(f"{tag}: lowered {t_lower:.1f}s, compiled {t_comp:.1f}s",
              flush=True)
        try:
            mem = compiled.memory_analysis()
            print(f"  per-device arguments "
                  f"{mem.argument_size_in_bytes/1e9:.2f} GB, "
                  f"temp {mem.temp_size_in_bytes/1e9:.2f} GB", flush=True)
        except Exception:
            pass
    print("7B TP compile-check OK")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
