"""Automatic prefix caching on the paged serving stack (ISSUE 5).

Drives a shared-system-prompt workload — the canonical serving shape:
every request is ``system_prompt + short user tail`` — through
``ContinuousBatchingServer(cache_backend="paged")`` twice, with
``auto_prefix_cache`` OFF and ON, and reports:

- auto hit rate (hits / requests; the first request per unique prefix
  run is necessarily cold),
- prefill tokens per mode and the tokens SAVED by page reuse (the
  counter-backed number that generalizes — host wall time on a CPU
  bench is dominated by XLA dispatch, not the avoided FLOPs),
- cached/pinned/free page occupancy at drain, plus eviction churn when
  ``--num-pages`` squeezes the pool,
- drain wall time per mode (best of N reps, compiles warmed first;
  noise-prone on shared CI — trust the counters).

    python benchmarks/prefix_cache_bench.py [--requests N]
        [--system-tokens N] [--tail-tokens N] [--new-tokens N]
        [--slots N] [--num-pages N] [--reps N]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _prompts(args):
    rng = np.random.default_rng(0)
    system = rng.integers(0, 256, (args.system_tokens,)).astype(np.int32)
    return [np.concatenate(
        [system, rng.integers(0, 256, (args.tail_tokens,))
         .astype(np.int32)]) for _ in range(args.requests)]


def _drain(model, prompts, args, auto):
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    srv = ContinuousBatchingServer(
        model, max_slots=args.slots, max_cache_len=args.max_cache_len,
        cache_backend="paged", page_size=args.page_size,
        num_pages=args.num_pages, auto_prefix_cache=auto)
    for p in prompts[:args.slots]:                  # warm the compiles
        srv.submit(p, max_new_tokens=2)
    srv.run()
    best = float("inf")
    for _ in range(args.reps):
        t0 = time.perf_counter()
        rids = [srv.submit(p, max_new_tokens=args.new_tokens)
                for p in prompts]
        outs = srv.run()
        best = min(best, time.perf_counter() - t0)
        assert all(r in outs for r in rids)
    return best, srv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--system-tokens", type=int, default=24)
    ap.add_argument("--tail-tokens", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-cache-len", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=None)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    model = _build_model()
    prompts = _prompts(args)
    t_off, off = _drain(model, prompts, args, auto=False)
    t_on, on = _drain(model, prompts, args, auto=True)

    n_req = args.requests * args.reps + args.slots  # incl. warmup
    hits = on.stats["prefix_auto_hits"]
    hit_tok = on.stats["prefix_auto_hit_tokens"]
    saved = off.stats["prefill_tokens"] - on.stats["prefill_tokens"]
    free, live, pinned, cached = on.pool_balance()
    shared_run = args.system_tokens // args.page_size * args.page_size

    print(f"workload: {args.requests} requests x {args.reps} reps "
          f"(+{args.slots} warmup), system {args.system_tokens} tok "
          f"(shared page run {shared_run}), tail {args.tail_tokens}, "
          f"{args.new_tokens} new")
    print(f"auto hit rate     : {hits}/{n_req} = {hits / n_req:.2f}  "
          f"({hit_tok} tokens served from cached pages)")
    print(f"prefill tokens    : off {off.stats['prefill_tokens']}, "
          f"on {on.stats['prefill_tokens']}  (saved {saved}, "
          f"{saved / max(off.stats['prefill_tokens'], 1) * 100:.0f}%)")
    print(f"pool at drain     : free {free}, live {live}, "
          f"pinned {pinned}, cached {cached} "
          f"(evicted {on._prefix.evicted_pages_total}, "
          f"donated {on._prefix.donated_pages_total})")
    print(f"drain wall (best) : off {t_off * 1e3:8.1f} ms, "
          f"on {t_on * 1e3:8.1f} ms  (counters are the signal; CPU "
          f"wall time is dispatch-dominated)")
    ok = hits >= (n_req - 1) * 0.9 and saved > 0 and live == 0
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
