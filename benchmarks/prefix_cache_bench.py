"""Automatic prefix caching + ragged prefill on the paged serving stack
(ISSUES 5 + 6).

Drives a shared-system-prompt workload — the canonical serving shape:
every request is ``system_prompt + short user tail`` — through
``ContinuousBatchingServer(cache_backend="paged")`` in three modes:

- ``auto off``   no prefix reuse, dense per-admission prefill,
- ``dense  on``  auto prefix cache + the PR-5 dense prefill path (every
  auto hit pays the page-gather -> dense-seed -> scatter detour),
- ``ragged on``  auto prefix cache + batched ragged prefill straight
  into pool pages (ISSUE 6, the paged default),
- ``fused  on``  auto prefix cache + the FUSED serving tick (ISSUE 14,
  ``serving_mode="fused"``): every admission tick is ONE launch —
  prefill chunks and decode rows together over a live-page DMA
  schedule — so TTFT sheds the split path's per-admission dispatch
  overhead,

and reports:

- steady-state auto hit rate: hits / (requests - expected cold misses).
  The warmup admissions are submitted together BEFORE any donation has
  happened, so each is a structurally-guaranteed miss (BENCHNOTES
  Round 7 recorded them as "4 misses" without the exclusion) — the raw
  rate is printed alongside,
- prefill tokens per mode and the tokens SAVED by page reuse (the
  counter-backed number that generalizes),
- admission-path DISPATCHES per admission (``prefill_dispatches`` /
  ``admissions``) — the ISSUE 6 acceptance signal: ragged must drop
  this vs the dense-on baseline,
- TTFT p50/p99 (measured at the first ``on_token`` callback) and the
  prefill wall-clock split (``prefill_wall_s``) per mode,
- cached/pinned/free page occupancy at drain, plus eviction churn when
  ``--num-pages`` squeezes the pool,
- drain wall time per mode (best of N reps, compiles warmed first;
  noise-prone on shared CI — trust the counters).

Then the MULTI-TURN SESSION workload (ISSUE 17): N users each serve a
distinct first turn, then every user RETURNS with a second turn that
extends their own history (turn-1 prompt + its generated tokens + a
fresh tail — only an extension of the donated prompt run can re-hit
its pages). The pool is squeezed so the first turns' donated pages
cannot all stay HBM-resident, and the same workload runs twice at
EQUAL device pool size: ``host_tier=None`` (evictions drop pages —
the pre-tier stack) vs ``HostTier()`` (evictions spill to host, the
returning turn restores). The bench self-asserts that the tiered run's
turn-2 hit tokens STRICTLY beat the HBM-only run's, that restores
actually happened (none corrupt), and that both runs' outputs are
bit-identical — the tier changes residency, never tokens.

    python benchmarks/prefix_cache_bench.py [--requests N]
        [--system-tokens N] [--tail-tokens N] [--new-tokens N]
        [--slots N] [--num-pages N] [--reps N] [--budget N]
        [--sessions N] [--session-tokens N] [--session-new N] [--track]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _prompts(args):
    rng = np.random.default_rng(0)
    system = rng.integers(0, 256, (args.system_tokens,)).astype(np.int32)
    return [np.concatenate(
        [system, rng.integers(0, 256, (args.tail_tokens,))
         .astype(np.int32)]) for _ in range(args.requests)]


def _drain(model, prompts, args, auto, prefill_mode,
           serving_mode="split"):
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    srv = ContinuousBatchingServer(
        model, max_slots=args.slots, max_cache_len=args.max_cache_len,
        cache_backend="paged", page_size=args.page_size,
        num_pages=args.num_pages, auto_prefix_cache=auto,
        prefill_mode=prefill_mode, serving_mode=serving_mode,
        prefill_tokens_per_tick=args.budget)
    for p in prompts[:args.slots]:                  # warm the compiles
        srv.submit(p, max_new_tokens=2)
    srv.run()
    for p in prompts[:2]:       # warm the HIT path's programs too (the
        srv.submit(p, max_new_tokens=2)   # remainder chunk geometry
    srv.run()                             # differs from the cold one)
    n_warm = min(args.requests, args.slots) + min(args.requests, 2)
    if serving_mode == "fused":
        # the fused (C, W, G) geometry ladder depends on the FULL
        # admission mix — one untimed full pass keeps ladder compiles
        # out of the timed reps' TTFT tail
        for p in prompts:
            srv.submit(p, max_new_tokens=args.new_tokens)
        srv.run()
        n_warm += args.requests
    best = float("inf")
    ttfts = []
    for _ in range(args.reps):
        first_seen = {}

        def on_token(rid, toks):
            if rid not in first_seen:
                first_seen[rid] = time.perf_counter()

        t0 = time.perf_counter()
        submits = {srv.submit(p, max_new_tokens=args.new_tokens,
                              on_token=on_token): time.perf_counter()
                   for p in prompts}
        outs = srv.run()
        best = min(best, time.perf_counter() - t0)
        assert all(r in outs for r in submits)
        ttfts += [first_seen[r] - t for r, t in submits.items()
                  if r in first_seen]
    return best, ttfts, srv, n_warm


def _session_bench(model, args, host_tier):
    """One pass of the multi-turn session workload. Serving config is
    pinned (1 slot, page 8, 7-page pool) so the two passes compare at
    EQUAL device memory and the pool genuinely cannot hold every
    user's history: 16-token turn-1 prompts donate 2 full pages each,
    so by the later users the earlier users' pages have been evicted
    — dropped when ``host_tier`` is None, spilled when it is on."""
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    srv = ContinuousBatchingServer(
        model, max_slots=1, max_cache_len=64, cache_backend="paged",
        page_size=8, num_pages=7, auto_prefix_cache=True,
        prefill_mode="ragged", host_tier=host_tier)
    rng = np.random.default_rng(1)
    users = [rng.integers(0, 256, (args.session_tokens,))
             .astype(np.int32) for _ in range(args.sessions)]
    outs1 = []
    for p in users:                         # turn 1: distinct histories
        rid = srv.submit(p, max_new_tokens=args.session_new)
        outs1.append(np.asarray(srv.run()[rid]))
    h_tok0 = srv.stats["prefix_auto_hit_tokens"]
    outs2 = []
    for p, o in zip(users, outs1):          # turn 2: extend OWN history
        ext = np.concatenate([p, o.astype(np.int32),
                              rng.integers(0, 256, (2,))
                              .astype(np.int32)])
        rid = srv.submit(ext, max_new_tokens=args.session_new)
        outs2.append(np.asarray(srv.run()[rid]))
    tier = srv.host_tier
    free, live, pinned, cached = srv.pool_balance()
    return {"hit_tokens": srv.stats["prefix_auto_hit_tokens"] - h_tok0,
            "outs": outs1 + outs2, "live": live,
            "spilled": tier.spilled_pages_total if tier else 0,
            "restored": tier.restored_pages_total if tier else 0,
            "corrupt": tier.restore_corrupt_total if tier else 0,
            "host_stats": tier.stats() if tier else None}


def _row(name, t_wall, ttfts, srv):
    s = srv.stats
    disp = s["prefill_dispatches"] / max(s["admissions"], 1)
    p50, p99 = (np.percentile(ttfts, 50) * 1e3,
                np.percentile(ttfts, 99) * 1e3) if ttfts else (0, 0)
    print(f"{name:10s}: prefill {s['prefill_tokens']:6d} tok, "
          f"{disp:5.2f} disp/admission, "
          f"prefill wall {s['prefill_wall_s'] * 1e3:7.1f} ms, "
          f"TTFT p50 {p50:6.1f} / p99 {p99:6.1f} ms, "
          f"drain best {t_wall * 1e3:7.1f} ms")
    return disp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--system-tokens", type=int, default=24)
    ap.add_argument("--tail-tokens", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-cache-len", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=None)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--budget", type=int, default=None,
                    help="prefill_tokens_per_tick (ragged mode)")
    ap.add_argument("--sessions", type=int, default=6,
                    help="returning users in the multi-turn workload")
    ap.add_argument("--session-tokens", type=int, default=16,
                    help="turn-1 prompt tokens per user (2 donated "
                         "pages at the pinned page size 8)")
    ap.add_argument("--session-new", type=int, default=4)
    ap.add_argument("--track", action="store_true",
                    help="append fused TTFT + tiered-session rounds "
                         "to BENCHLOG.jsonl")
    args = ap.parse_args()

    model = _build_model()
    prompts = _prompts(args)
    t_off, tt_off, off, _ = _drain(model, prompts, args, auto=False,
                                   prefill_mode="dense")
    t_dn, tt_dn, dense_on, w_dn = _drain(model, prompts, args,
                                         auto=True,
                                         prefill_mode="dense")
    t_rg, tt_rg, ragged, w_rg = _drain(model, prompts, args, auto=True,
                                       prefill_mode="ragged")
    t_fu, tt_fu, fused, w_fu = _drain(model, prompts, args, auto=True,
                                      prefill_mode="ragged",
                                      serving_mode="fused")

    # per-server admission counts incl. warmup (_drain returns how
    # many warmers it submitted; only the FIRST wave — submitted
    # before any donation — is structurally cold)
    warm = min(args.requests, args.slots)   # pre-donation => cold
    shared_run = args.system_tokens // args.page_size * args.page_size

    print(f"workload: {args.requests} requests x {args.reps} reps "
          f"(+{warm} warmup), system {args.system_tokens} tok "
          f"(shared page run {shared_run}), tail {args.tail_tokens}, "
          f"{args.new_tokens} new")
    _row("auto off", t_off, tt_off, off)
    d_dn = _row("dense  on", t_dn, tt_dn, dense_on)
    d_rg = _row("ragged on", t_rg, tt_rg, ragged)
    d_fu = _row("fused  on", t_fu, tt_fu, fused)

    ok = True
    for name, srv, n_warm in (("dense", dense_on, w_dn),
                              ("ragged", ragged, w_rg),
                              ("fused", fused, w_fu)):
        n_req = args.requests * args.reps + n_warm
        hits = srv.stats["prefix_auto_hits"]
        steady = hits / max(n_req - warm, 1)
        print(f"{name:6s} hit rate  : steady-state {hits}/{n_req - warm}"
              f" = {steady:.2f}  (raw {hits}/{n_req} = "
              f"{hits / n_req:.2f}; the {warm} warmup admissions are "
              f"structurally cold)")
        saved = off.stats["prefill_tokens"] - srv.stats["prefill_tokens"]
        print(f"{name:6s} saved     : {saved} prefill tokens "
              f"({saved / max(off.stats['prefill_tokens'], 1) * 100:.0f}"
              f"% of cold)")
        free, live, pinned, cached = srv.pool_balance()
        print(f"{name:6s} pool      : free {free}, live {live}, pinned "
              f"{pinned}, cached {cached} (evicted "
              f"{srv._prefix.evicted_pages_total}, donated "
              f"{srv._prefix.donated_pages_total})")
        ok = ok and steady >= 0.95 and saved > 0 and live == 0
    # ISSUE 6 acceptance: ragged kills the auto-hit dispatch detour
    print(f"dispatch ratio    : ragged {d_rg:.2f} vs dense-on {d_dn:.2f}"
          f" per admission ({'OK' if d_rg < d_dn else 'REGRESSION'}; "
          f"counters are the signal, CPU wall time is "
          f"dispatch-dominated)")
    ok = ok and d_rg < d_dn
    # ISSUE 14: the fused tick IS the admission dispatch — exactly one
    # launch carries each admission wave's chunks
    print(f"fused  dispatches : {d_fu:.2f} per admission "
          f"({'OK' if d_fu <= d_rg else 'REGRESSION'}; the launch "
          f"doubles as the decode tick)")
    ok = ok and d_fu <= d_rg

    # ISSUE 17: multi-turn sessions — N users return to their own
    # history under a pool too small to keep it all HBM-resident
    from paddle_tpu.inference.kv_tier import HostTier
    hbm = _session_bench(model, args, None)
    tiered = _session_bench(model, args, HostTier())
    ideal = args.sessions * (args.session_tokens // 8) * 8
    t_rate = tiered["hit_tokens"] / max(ideal, 1)
    h_rate = hbm["hit_tokens"] / max(ideal, 1)
    print(f"\nsessions ({args.sessions} users x 2 turns, 7-page pool "
          f"both runs):")
    print(f"hbm-only  turn 2  : {hbm['hit_tokens']:4d}/{ideal} hit "
          f"tokens ({h_rate:.2f}) — evictions DROPPED the history")
    hs = tiered["host_stats"]
    print(f"tiered    turn 2  : {tiered['hit_tokens']:4d}/{ideal} hit "
          f"tokens ({t_rate:.2f}), spilled "
          f"{tiered['spilled']} pages, restored {tiered['restored']}, "
          f"corrupt {tiered['corrupt']}; host now holds "
          f"{hs['entries']} pages / {hs['bytes_used']} bytes")
    sess_ok = (tiered["hit_tokens"] > hbm["hit_tokens"]
               and tiered["restored"] > 0 and tiered["corrupt"] == 0
               and tiered["live"] == 0 and hbm["live"] == 0
               and all(np.array_equal(a, b) for a, b
                       in zip(hbm["outs"], tiered["outs"])))
    print(f"session guard     : tiered strictly beats hbm-only at "
          f"equal device memory, outputs bit-identical "
          f"({'OK' if sess_ok else 'REGRESSION'})")
    ok = ok and sess_ok
    if args.track:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_track", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "scripts", "bench_track.py"))
        bench_track = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench_track)
        p50 = float(np.percentile(tt_fu, 50) * 1e3) if tt_fu else 0.0
        r = bench_track.append_round(
            {"metric": "fused_prefix_ttft_p50_ms", "value": p50,
             "unit": "ms",
             "note": f"{args.requests} reqs x {args.reps} reps, "
                     f"system {args.system_tokens} tok, CPU "
                     f"llama_tiny; serving_mode=fused"})
        print(f"tracked {r['metric']} = {r['value']:.1f}")
        r2 = bench_track.append_round(
            {"metric": "tiered_session_turn2_hit_rate", "value": t_rate,
             "unit": "ratio",
             "note": f"{args.sessions} users x 2 turns, 7-page pool, "
                     f"host tier on (hbm-only baseline {h_rate:.2f}); "
                     f"restored {tiered['restored']} pages"})
        print(f"tracked {r2['metric']} = {r2['value']:.2f}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
