"""Telemetry overhead on the continuous-batching decode path (ISSUE 2).

Drives the same request workload through ``ContinuousBatchingServer``
with telemetry DISABLED (``telemetry=None`` — one attribute check per
hook site) and ENABLED (full ``ServerTelemetry``: histograms, gauges,
spans) and reports:

- drain wall time per mode (best of N reps, compile warmed first),
- per-tick decode latency from the enabled run's own
  ``serving_tick_seconds`` histogram (telemetry measuring itself),
- instrument microbenchmarks (counter.inc / histogram.observe /
  null-instrument call, ns/op),
- the enabled-vs-disabled overhead %% — target: <2%% on the CPU decode
  bench (the real tick is milliseconds of XLA work; the instruments
  add microseconds of host work).

    python benchmarks/telemetry_overhead_bench.py [--slots N]
        [--requests N] [--new-tokens N] [--reps N]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _drain(model, telemetry, slots, requests, new_tokens, reps):
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 256, (int(rng.integers(4, 12)),))
               .astype(np.int32) for _ in range(requests)]
    srv = ContinuousBatchingServer(model, max_slots=slots,
                                   max_cache_len=128,
                                   telemetry=telemetry)
    for p in prompts[:slots]:                       # warm the compiles
        srv.submit(p, max_new_tokens=4)
    srv.run()
    best = float("inf")
    for _ in range(reps):
        for p in prompts:
            srv.submit(p, max_new_tokens=new_tokens)
        t0 = time.perf_counter()
        srv.run()
        best = min(best, time.perf_counter() - t0)
    return best, srv


def _micro(fn, n=200_000):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n * 1e9     # ns/op


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    from paddle_tpu.telemetry import MetricRegistry, ServerTelemetry

    model = _build_model()
    t_off, _ = _drain(model, None, args.slots, args.requests,
                      args.new_tokens, args.reps)
    tele = ServerTelemetry()
    t_on, srv = _drain(model, tele, args.slots, args.requests,
                       args.new_tokens, args.reps)

    tick = tele.registry.get("serving_tick_seconds")
    overhead = (t_on - t_off) / t_off * 100.0

    reg = MetricRegistry()
    c = reg.counter("bench_total")
    h = reg.histogram("bench_seconds")
    null = MetricRegistry(enabled=False).counter("off_total")
    ns_inc = _micro(c.inc)
    ns_obs = _micro(lambda: h.observe(0.003))
    ns_null = _micro(null.inc)

    print(f"workload: {args.requests} requests x {args.new_tokens} new "
          f"tokens, {args.slots} slots, best of {args.reps}")
    print(f"drain disabled : {t_off * 1e3:9.1f} ms")
    print(f"drain enabled  : {t_on * 1e3:9.1f} ms   "
          f"({tick.count} ticks, "
          f"{tick.sum / max(tick.count, 1) * 1e3:.3f} ms/tick measured "
          f"by serving_tick_seconds)")
    print(f"overhead       : {overhead:9.2f} %   (target < 2%)")
    print(f"counter.inc    : {ns_inc:9.0f} ns/op")
    print(f"hist.observe   : {ns_obs:9.0f} ns/op")
    print(f"null inc       : {ns_null:9.0f} ns/op (disabled registry)")
    return 0 if overhead < 2.0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
