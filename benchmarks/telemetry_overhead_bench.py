"""Telemetry overhead on the continuous-batching decode path (ISSUE 2;
recorder + journey paths added by ISSUE 10, goodput ledger by
ISSUE 11).

Drives the same request workload through ``ContinuousBatchingServer``
with telemetry DISABLED (``telemetry=None`` — one attribute check per
hook site) and ENABLED (full ``ServerTelemetry``: histograms, gauges,
spans), then again with a ``FlightRecorder`` attached DISABLED
(``enabled=False`` — must be structurally free: the server treats it
as None) and ENABLED (event ring + per-tick dispatch profiles), then
the same pair for the ``GoodputLedger`` (disabled = treated as None;
enabled = per-token attribution + per-tick flush), then the
``HostTier`` pair (ISSUE 17) on a squeezed PAGED pool — disabled
(``HostTier(enabled=False)``) must be treated as None while enabled
pays real spill/restore device transfers — and reports:

- drain wall time per mode (best of N reps, compile warmed first),
- per-tick decode latency from the enabled run's own
  ``serving_tick_seconds`` histogram (telemetry measuring itself),
- the enabled ledger run's steady-state goodput ratio,
- instrument microbenchmarks (counter.inc / histogram.observe /
  null-instrument call / recorder.record / disabled record / journey
  event / ledger add+flush, ns/op),
- the enabled-vs-disabled overhead %% per layer — GUARDS: telemetry
  <2%%, disabled-recorder <2%%, disabled-ledger <2%%,
  disabled-cost-catalog <2%%, disabled-host-tier <2%% (the
  disabled-is-structurally-zero-cost contract, measured end to end
  rather than assumed). The cost catalog's ENABLED pair (ISSUE 13)
  additionally reports the AOT pricing + compile-watch + phase-clock
  cost and the run's decode FLOPs/MFU; the host tier's reports pages
  spilled/restored and resident host bytes.

    python benchmarks/telemetry_overhead_bench.py [--slots N]
        [--requests N] [--new-tokens N] [--reps N]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(21)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


def _drain(model, telemetry, slots, requests, new_tokens, reps,
           recorder=None, ledger=None, costs=None, **srv_kw):
    from paddle_tpu.inference.continuous_batching import \
        ContinuousBatchingServer
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 256, (int(rng.integers(4, 12)),))
               .astype(np.int32) for _ in range(requests)]
    srv = ContinuousBatchingServer(model, max_slots=slots,
                                   max_cache_len=128,
                                   telemetry=telemetry,
                                   recorder=recorder, ledger=ledger,
                                   costs=costs, **srv_kw)
    for p in prompts[:slots]:                       # warm the compiles
        srv.submit(p, max_new_tokens=4)
    srv.run()
    best = float("inf")
    for _ in range(reps):
        for p in prompts:
            srv.submit(p, max_new_tokens=new_tokens)
        t0 = time.perf_counter()
        srv.run()
        best = min(best, time.perf_counter() - t0)
    return best, srv


def _micro(fn, n=200_000):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n * 1e9     # ns/op


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    from paddle_tpu.telemetry import (CostCatalog, FlightRecorder,
                                      GoodputLedger, JourneyRecorder,
                                      MetricRegistry, ServerTelemetry)

    model = _build_model()
    t_off, _ = _drain(model, None, args.slots, args.requests,
                      args.new_tokens, args.reps)
    tele = ServerTelemetry()
    t_on, srv = _drain(model, tele, args.slots, args.requests,
                       args.new_tokens, args.reps)
    # recorder/ledger paths ride on the DISABLED-telemetry baseline so
    # each layer's cost is isolated
    t_rec_off, _ = _drain(model, None, args.slots, args.requests,
                          args.new_tokens, args.reps,
                          recorder=FlightRecorder(enabled=False))
    rec = FlightRecorder()
    t_rec_on, srv_rec = _drain(model, None, args.slots, args.requests,
                               args.new_tokens, args.reps, recorder=rec)
    t_led_off, _ = _drain(model, None, args.slots, args.requests,
                          args.new_tokens, args.reps,
                          ledger=GoodputLedger(enabled=False))
    led = GoodputLedger()
    t_led_on, _ = _drain(model, None, args.slots, args.requests,
                         args.new_tokens, args.reps, ledger=led)
    # cost catalog + compile watch pair (ISSUE 13): disabled must be
    # structurally free; enabled pays AOT pricing + phase clock reads
    t_cost_off, _ = _drain(model, None, args.slots, args.requests,
                           args.new_tokens, args.reps,
                           costs=CostCatalog(enabled=False))
    cat = CostCatalog()
    t_cost_on, _ = _drain(model, None, args.slots, args.requests,
                          args.new_tokens, args.reps, costs=cat)
    # host-tier pair (ISSUE 17) rides on a PAGED baseline (the tier
    # needs the paged backend) with a pool squeezed so donated prefix
    # pages actually evict: disabled (HostTier(enabled=False)) must be
    # treated as None — structurally free — while enabled pays real
    # spill gathers on evict and restore scatters when the reps re-hit
    from paddle_tpu.inference.kv_tier import HostTier
    pg_kw = {"cache_backend": "paged", "page_size": 8, "num_pages": 44}
    t_pg, _ = _drain(model, None, args.slots, args.requests,
                     args.new_tokens, args.reps, **pg_kw)
    t_ht_off, _ = _drain(model, None, args.slots, args.requests,
                         args.new_tokens, args.reps,
                         host_tier=HostTier(enabled=False), **pg_kw)
    tier = HostTier()
    t_ht_on, _ = _drain(model, None, args.slots, args.requests,
                        args.new_tokens, args.reps, host_tier=tier,
                        **pg_kw)

    tick = tele.registry.get("serving_tick_seconds")
    overhead = (t_on - t_off) / t_off * 100.0
    rec_off_overhead = (t_rec_off - t_off) / t_off * 100.0
    rec_on_overhead = (t_rec_on - t_off) / t_off * 100.0
    led_off_overhead = (t_led_off - t_off) / t_off * 100.0
    led_on_overhead = (t_led_on - t_off) / t_off * 100.0
    cost_off_overhead = (t_cost_off - t_off) / t_off * 100.0
    cost_on_overhead = (t_cost_on - t_off) / t_off * 100.0
    ht_off_overhead = (t_ht_off - t_pg) / t_pg * 100.0
    ht_on_overhead = (t_ht_on - t_pg) / t_pg * 100.0
    goodput = led.snapshot()
    cost_snap = cat.snapshot()

    reg = MetricRegistry()
    c = reg.counter("bench_total")
    h = reg.histogram("bench_seconds")
    null = MetricRegistry(enabled=False).counter("off_total")
    ns_inc = _micro(c.inc)
    ns_obs = _micro(lambda: h.observe(0.003))
    ns_null = _micro(null.inc)
    mrec = FlightRecorder(capacity=4096)
    ns_rec = _micro(lambda: mrec.record("bench", rid=1))
    drec = FlightRecorder(enabled=False)
    ns_rec_off = _micro(lambda: drec.record("bench", rid=1))
    jr = JourneyRecorder()
    jh = jr.begin("bench")
    ns_jev = _micro(lambda: jh.event("phase", rid=1))
    mled = GoodputLedger()
    ns_ladd = _micro(lambda: mled.add("goodput", 1))

    def _add_flush():
        mled.add("goodput", 1)
        mled.flush_tick()
    ns_lflush = _micro(_add_flush, n=50_000)

    print(f"workload: {args.requests} requests x {args.new_tokens} new "
          f"tokens, {args.slots} slots, best of {args.reps}")
    print(f"drain disabled      : {t_off * 1e3:9.1f} ms")
    print(f"drain telemetry     : {t_on * 1e3:9.1f} ms   "
          f"({tick.count} ticks, "
          f"{tick.sum / max(tick.count, 1) * 1e3:.3f} ms/tick measured "
          f"by serving_tick_seconds)")
    print(f"drain rec disabled  : {t_rec_off * 1e3:9.1f} ms   "
          f"({rec_off_overhead:+.2f}% — structurally-zero guard)")
    print(f"drain rec enabled   : {t_rec_on * 1e3:9.1f} ms   "
          f"({rec_on_overhead:+.2f}%, {rec.total} events, "
          f"{len(rec.events(kind='tick'))} tick profiles)")
    print(f"drain ledger off    : {t_led_off * 1e3:9.1f} ms   "
          f"({led_off_overhead:+.2f}% — structurally-zero guard)")
    print(f"drain ledger on     : {t_led_on * 1e3:9.1f} ms   "
          f"({led_on_overhead:+.2f}%, goodput ratio "
          f"{goodput['goodput_ratio']:.3f} over {goodput['ticks']} "
          f"ticks)")
    print(f"drain costs off     : {t_cost_off * 1e3:9.1f} ms   "
          f"({cost_off_overhead:+.2f}% — structurally-zero guard)")
    dec_cost = cost_snap["ops"].get("decode", {"flops": 0})
    print(f"drain costs on      : {t_cost_on * 1e3:9.1f} ms   "
          f"({cost_on_overhead:+.2f}%, {cost_snap['compiles']} "
          f"compiles, decode {dec_cost['flops']:.3g} FLOPs, "
          f"mfu {cost_snap['mfu'] or 0:.2e})")
    print(f"drain paged base    : {t_pg * 1e3:9.1f} ms   "
          f"(host-tier pair baseline: squeezed 44-page pool)")
    print(f"drain host-tier off : {t_ht_off * 1e3:9.1f} ms   "
          f"({ht_off_overhead:+.2f}% — structurally-zero guard)")
    print(f"drain host-tier on  : {t_ht_on * 1e3:9.1f} ms   "
          f"({ht_on_overhead:+.2f}%, spilled "
          f"{tier.spilled_pages_total} pages, restored "
          f"{tier.restored_pages_total}, "
          f"{tier.stats()['bytes_used']} host bytes resident)")
    print(f"telemetry overhead  : {overhead:9.2f} %   (target < 2%)")
    print(f"counter.inc         : {ns_inc:9.0f} ns/op")
    print(f"hist.observe        : {ns_obs:9.0f} ns/op")
    print(f"null inc            : {ns_null:9.0f} ns/op (disabled registry)")
    print(f"recorder.record     : {ns_rec:9.0f} ns/op")
    print(f"record (disabled)   : {ns_rec_off:9.0f} ns/op")
    print(f"journey.event       : {ns_jev:9.0f} ns/op")
    print(f"ledger.add          : {ns_ladd:9.0f} ns/op")
    print(f"ledger add+flush    : {ns_lflush:9.0f} ns/op")
    # guards: full telemetry <2%, DISABLED recorder <2%, DISABLED
    # ledger <2%, DISABLED cost catalog <2%, DISABLED host tier <2%
    # vs its paged baseline (their events/clock reads are asserted
    # zero in tests; wall clock is the end-to-end check that "treated
    # as None" holds)
    return 0 if (overhead < 2.0 and rec_off_overhead < 2.0
                 and led_off_overhead < 2.0
                 and cost_off_overhead < 2.0
                 and ht_off_overhead < 2.0) else 1


if __name__ == "__main__":
    raise SystemExit(main())
