"""Colocated vs disaggregated prefill/decode serving (ISSUE 20): what
specialist replicas + the pipelined page handoff buy under mixed load.

Colocated serving runs every replica as a hybrid: long-prompt prefill
chunks and single-token decode ticks interleave on the same device, so
a decode-heavy stream's inter-token gap (TPOT) spikes every time a
prefill chunk lands in front of it. Disaggregated serving routes long
prompts to a prefill specialist, streams the written KV pages to a
decode specialist in bounded multi-frame batches as chunks complete
(``migrate_out(partial=True)``), and commits sampler state + the tail
pages at the cut — the decode specialist never runs a long prompt's
prefill at all.

This bench drives the SAME greedy workload — a decode-heavy stream of
short prompts plus a steady arrival of long prompts — through both
fleet shapes at EQUAL replica count (2 hybrids vs 1 prefill + 1
decode) and reports, per mode:

- decode TPOT p50/p99 over the short streams (wall-clock gaps between
  streamed tokens; the ratio disagg/colocated is the tracked metric),
- long-prompt TTFT p50,
- handoffs completed vs fallbacks, and the fleet-wide re-prefill bill:
  sum(prefill_tokens across replicas) - sum(prompt lens). The
  disaggregated mode SELF-ASSERTS this is exactly 0 — any re-prefilled
  token means the handoff fell back to replay,
- the decode specialist's steady-state dispatch profile from the
  flight recorder: every pure-decode tick must stay ``{"decode": 1}``
  (one launch per tick — the handoff scatters pages off-tick).

Correctness phases run before any timing and hard-assert:

- greedy AND seeded-sampled empty-``emitted`` handoff parity vs a
  single-replica oracle (zero re-prefill both ways),
- a fused-mode decode specialist accepting a mid-prefill handoff:
  bit-exact, steady ticks all ``{"fused": 1}``,
- mp1<->mp2 cross-topology mid-prefill handoff on a real tiny llama
  (skipped with a printed note when < 2 devices; run under
  ``XLA_FLAGS=--xla_force_host_platform_device_count=2`` to price it).

Every completed stream is verified bit-exact against its oracle, so a
mode that cheated correctness would fail before it reported a number.

    python benchmarks/disagg_bench.py [--shorts N] [--longs N]
        [--short-prompt N] [--long-prompt N] [--track]
"""
import argparse
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))
sys.path.insert(0, os.path.join(_REPO, "scripts"))


# ------------------------------------------------------------------ #
# correctness phases (step-driven, deterministic)                    #
# ------------------------------------------------------------------ #

def _drain(*servers, cap=200_000):
    for _ in range(cap):
        busy = False
        for s in servers:
            if s._busy_locked():
                s.step()
                busy = True
        if not busy:
            return
    raise AssertionError("servers never drained")


def _parity_phase(args):
    """Empty-``emitted`` handoff parity, greedy + seeded-sampled, plus
    the fused-target dispatch profile. Returns the re-prefill bill
    (asserted 0)."""
    from _remote_stub import make_stub_server
    from _serving_stub import stub_tokens
    from paddle_tpu.telemetry import FlightRecorder

    kw = dict(max_cache_len=64, num_pages=24, prefill_tokens_per_tick=8)
    rng = np.random.default_rng(20)
    prompt = rng.integers(0, 16, (24,)).astype(np.int32)
    budget = 12
    reprefill = 0

    def handoff(src, tgt, seed=None):
        rid = src.submit(prompt, max_new_tokens=budget, seed=seed)
        src.step(); src.step()          # 16 of 24 prompt tokens in
        state, payloads = src.migrate_out(rid)
        assert state["phase"] == "prefill", state["phase"]
        new = tgt.migrate_in(state, payloads)
        src.migrate_finish(rid)
        _drain(src, tgt)
        return tgt.wait(new, timeout=30)

    # greedy vs the closed-form oracle
    src = make_stub_server(role="prefill", **kw)
    tgt = make_stub_server(role="decode", **kw)
    np.testing.assert_array_equal(handoff(src, tgt),
                                  stub_tokens(prompt, budget))
    bill = src.stats["prefill_tokens"] + tgt.stats["prefill_tokens"] \
        - len(prompt)
    assert bill == 0, f"greedy handoff re-prefilled {bill} tokens"
    reprefill += bill

    # seeded-sampled vs a single-replica oracle run
    skw = dict(kw, do_sample=True, temperature=0.8, top_k=8)
    oracle = make_stub_server(**skw)
    orid = oracle.submit(prompt, max_new_tokens=budget, seed=5)
    _drain(oracle)
    src = make_stub_server(role="prefill", **skw)
    tgt = make_stub_server(role="decode", **skw)
    np.testing.assert_array_equal(handoff(src, tgt, seed=5),
                                  oracle.wait(orid, timeout=5))
    bill = src.stats["prefill_tokens"] + tgt.stats["prefill_tokens"] \
        - len(prompt)
    assert bill == 0, f"sampled handoff re-prefilled {bill} tokens"
    reprefill += bill
    for s in (src, tgt, oracle):
        assert s.pool_balance()[1] == 0, "leaked pages"

    # fused-mode decode specialist: the restored mid-prefill slot
    # finishes its prompt inside the megakernel tick and every steady
    # tick stays one launch
    rec = FlightRecorder()
    src = make_stub_server(role="prefill", **kw)
    tgt = make_stub_server(role="decode", serving_mode="fused",
                           prefill_mode="ragged", recorder=rec, **kw)
    np.testing.assert_array_equal(handoff(src, tgt),
                                  stub_tokens(prompt, budget))
    prof = [e["dispatches"] for e in rec.events()
            if e.get("kind") == "tick" and e.get("dispatches")]
    assert prof and all(d == {"fused": 1} for d in prof), prof
    print(f"parity: greedy + seeded-sampled handoff bit-exact, "
          f"re-prefill 0; fused target steady ticks all "
          f"{{'fused': 1}} ({len(prof)} ticks)")
    return reprefill


def _llama():
    """The 4-kv-head tiny llama every serving bench prices on: real
    matmuls, so a prefill chunk genuinely outweighs a decode tick."""
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=1,
                      num_heads=8, num_kv_heads=4,
                      intermediate_size=128, max_seq_len=256)
    pt.seed(21)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


def _cross_topology_phase(model):
    """Mid-prefill handoff across tensor-parallel layouts (mp2->mp1
    and mp1->mp2) on a real tiny llama with seeded sampling. Returns
    the re-prefill bill (0), or None when the host has < 2 devices."""
    import jax

    if len(jax.devices()) < 2:
        print("cross-topology: skipped (needs >= 2 devices; run under "
              "XLA_FLAGS=--xla_force_host_platform_device_count=2)")
        return None
    from jax.sharding import Mesh

    from paddle_tpu.inference import ContinuousBatchingServer

    def mesh(n):
        return Mesh(np.array(jax.devices()[:n]), ("mp",)) \
            if n > 1 else None

    kw = dict(max_slots=2, max_cache_len=64, cache_backend="paged",
              page_size=8, num_pages=24, do_sample=True,
              temperature=0.8, top_k=20, prefill_tokens_per_tick=8)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, 256, (20,)).astype(np.int32)
    budget = 16
    reprefill = 0
    for src_mp, tgt_mp in ((2, 1), (1, 2)):
        src = ContinuousBatchingServer(model, mesh=mesh(src_mp),
                                       role="prefill", **kw)
        tgt = ContinuousBatchingServer(model, mesh=mesh(tgt_mp),
                                       role="decode", **kw)
        oracle = ContinuousBatchingServer(model, **kw)
        orid = oracle.submit(prompt, max_new_tokens=budget, seed=31)
        _drain(oracle)
        rid = src.submit(prompt, max_new_tokens=budget, seed=31)
        src.step()                      # 8 of 20 prompt tokens in
        state, payloads = src.migrate_out(rid)
        assert state["phase"] == "prefill"
        new = tgt.migrate_in(state, payloads)
        src.migrate_finish(rid)
        _drain(src, tgt)
        np.testing.assert_array_equal(tgt.wait(new, timeout=120),
                                      oracle.wait(orid, timeout=5))
        bill = src.stats["prefill_tokens"] \
            + tgt.stats["prefill_tokens"] - len(prompt)
        assert bill == 0, \
            f"mp{src_mp}->mp{tgt_mp} re-prefilled {bill} tokens"
        reprefill += bill
        for s in (src, tgt):
            assert s.pool_balance()[1] == 0
        print(f"cross-topology: mp{src_mp}->mp{tgt_mp} mid-prefill "
              f"handoff bit-exact, re-prefill 0")
    return reprefill


# ------------------------------------------------------------------ #
# the timed fleet runs                                               #
# ------------------------------------------------------------------ #

def _server_kw(args):
    return dict(max_slots=args.slots, max_cache_len=args.max_cache_len,
                cache_backend="paged", page_size=args.page_size,
                num_pages=args.pool_pages,
                prefill_tokens_per_tick=args.chunk)


def _workload(args):
    """(key, prompt, budget) triples: a decode-heavy floor of short
    prompts plus a steady arrival of long prompts. Distinct random
    prompts so prefix-cache hits cannot hide a re-prefill."""
    rng = np.random.default_rng(20)
    reqs = []
    for i in range(args.shorts):
        reqs.append((("s", i),
                     rng.integers(0, 256,
                                  (args.short_prompt,)).astype(np.int32),
                     args.short_budget))
    for i in range(args.longs):
        reqs.append((("l", i),
                     rng.integers(0, 256,
                                  (args.long_prompt,)).astype(np.int32),
                     args.long_budget))
    return reqs


def _oracle_outputs(model, args, reqs):
    """Greedy reference streams: every request run SOLO on a single
    replica at the fleet geometry — the bar both fleet shapes must hit
    bit-exactly."""
    from paddle_tpu.inference import ContinuousBatchingServer

    srv = ContinuousBatchingServer(model, **_server_kw(args))
    exp = {}
    try:
        for k, p, budget in reqs:
            rid = srv.submit(p, max_new_tokens=budget)
            _drain(srv)
            exp[k] = srv.wait(rid, timeout=60)
    finally:
        srv.stop()
    return exp


def _fleet(args, mode, model, reqs, expected, warm=False):
    """One threaded fleet run at equal replica count: 2 hybrids under
    the default affinity placement ('colocated') vs prefill + decode
    specialists under placement='disaggregated'. Real tiny-llama
    replicas, so a prefill chunk costs real matmul time; greedy, so
    every stream is verified against the solo-run oracle. ``warm``
    runs the identical shape untimed first, keeping jit compiles (the
    handoff gather/scatter geometries especially) out of the timed
    pass."""
    from paddle_tpu.inference import (ContinuousBatchingServer,
                                      ReplicaRouter)
    from paddle_tpu.telemetry import FlightRecorder

    kw = _server_kw(args)
    rec = FlightRecorder()
    if mode == "disaggregated":
        reps = [ContinuousBatchingServer(model, role="prefill", **kw),
                ContinuousBatchingServer(model, role="decode",
                                         recorder=rec, **kw)]
        router = ReplicaRouter(
            reps, placement="disaggregated",
            disagg_prefill_min_tokens=args.disagg_min_tokens)
    else:
        reps = [ContinuousBatchingServer(model, role="hybrid", **kw),
                ContinuousBatchingServer(model, role="hybrid",
                                         recorder=rec, **kw)]
        router = ReplicaRouter(reps)

    lock = threading.Lock()
    times, toks = {}, {}

    def sink(key):
        times[key], toks[key] = [], []

        def cb(_r, ts):
            now = time.perf_counter()
            with lock:
                toks[key].extend(int(t) for t in ts)
                times[key].extend([now] * len(ts))
        return cb

    submitted, rids = {}, {}
    t0 = time.perf_counter()
    try:
        router.start(poll_interval=0.002)
        # decode-heavy floor first ...
        for k, p, budget in reqs:
            if k[0] != "s":
                continue
            rids[k] = router.submit(p, max_new_tokens=budget,
                                    on_token=sink(k))
            submitted[k] = time.perf_counter()
            time.sleep(0.004)
        time.sleep(0.05)                # let the shorts reach decode
        # ... then a steady arrival of long prompts on top of it
        for k, p, budget in reqs:
            if k[0] != "l":
                continue
            rids[k] = router.submit(p, max_new_tokens=budget,
                                    on_token=sink(k))
            submitted[k] = time.perf_counter()
            time.sleep(args.long_gap_s)
        outs = {k: router.wait(r, timeout=180)
                for k, r in rids.items()}
        wall = time.perf_counter() - t0
        # settle: a stream can complete on the target while the pump
        # is still releasing the source slot (migrate_finish) — give
        # the fleet a beat to return every page before the leak check
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(r.pool_balance()[1] == 0 for r in reps):
                break
            time.sleep(0.005)
    finally:
        router.stop()

    # correctness first: bit-exact streams, callbacks complete
    for k, out in outs.items():
        np.testing.assert_array_equal(out, expected[k])
        np.testing.assert_array_equal(np.asarray(toks[k]), out)
    # the re-prefill bill across the whole fleet: any token prefilled
    # twice shows up as an excess over the submitted prompt tokens
    prompt_tokens = sum(len(p) for _, p, _ in reqs)
    reprefill = sum(r.stats["prefill_tokens"] for r in reps) \
        - prompt_tokens
    for r in reps:
        assert r.pool_balance()[1] == 0, "leaked pages"

    gaps = []
    for i in range(args.shorts):
        ts = times[("s", i)]
        gaps.extend(np.diff(np.asarray(ts)))
    gaps = np.asarray(gaps)
    ttft = [times[("l", i)][0] - submitted[("l", i)]
            for i in range(args.longs)]

    out = {"mode": mode, "wall_s": wall,
           "tpot_p50_ms": float(np.percentile(gaps, 50)) * 1e3,
           "tpot_p99_ms": float(np.percentile(gaps, 99)) * 1e3,
           "ttft_p50_ms": float(np.percentile(ttft, 50)) * 1e3,
           "reprefill_tokens": int(reprefill),
           "handoffs": router.stats.get("handoffs", 0),
           "fallbacks": router.stats.get("handoff_fallbacks", 0)}
    if mode == "disaggregated":
        # the acceptance contract, asserted on every run
        assert reprefill == 0, \
            f"disaggregated fleet re-prefilled {reprefill} tokens"
        if not warm:
            assert out["handoffs"] >= 1, \
                "no prefill->decode handoff completed"
        # decode specialist's steady-state dispatch profile: every
        # pure-decode tick is ONE launch — the handoff scatters pages
        # off-tick, never as extra per-tick dispatches
        prof = [e["dispatches"] for e in rec.events()
                if e.get("kind") == "tick" and e.get("dispatches")]
        steady = [d for d in prof if set(d) <= {"decode"}]
        assert steady and all(d == {"decode": 1} for d in steady), \
            f"decode specialist tick profile drifted: {steady[:5]}"
        out["steady_decode_ticks"] = len(steady)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--shorts", type=int, default=10,
                    help="decode-heavy short requests")
    ap.add_argument("--longs", type=int, default=6,
                    help="prefill-heavy long requests")
    ap.add_argument("--short-prompt", type=int, default=8)
    ap.add_argument("--short-budget", type=int, default=60)
    ap.add_argument("--long-prompt", type=int, default=128)
    ap.add_argument("--long-budget", type=int, default=24)
    ap.add_argument("--long-gap-s", type=float, default=0.02,
                    help="arrival gap between long prompts")
    ap.add_argument("--chunk", type=int, default=16,
                    help="prefill tokens per tick")
    ap.add_argument("--slots", type=int, default=12)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--pool-pages", type=int, default=256)
    ap.add_argument("--max-cache-len", type=int, default=160)
    ap.add_argument("--disagg-min-tokens", type=int, default=32,
                    help="prompt length that routes to a prefill "
                         "specialist")
    ap.add_argument("--track", action="store_true",
                    help="append disaggregation rounds to "
                         "BENCHLOG.jsonl")
    args = ap.parse_args(argv)
    if args.long_prompt + args.long_budget > args.max_cache_len:
        ap.error("long prompt + budget must fit max_cache_len")
    if not (args.short_prompt < args.disagg_min_tokens
            <= args.long_prompt):
        ap.error("disagg-min-tokens must split shorts from longs")

    reprefill = _parity_phase(args)
    model = _llama()
    xbill = _cross_topology_phase(model)
    if xbill is not None:
        reprefill += xbill

    reqs = _workload(args)
    expected = _oracle_outputs(model, args, reqs)
    print("oracle: solo-run reference streams computed "
          f"({len(reqs)} requests)")
    # untimed warm pass per mode: compiles (handoff gather/scatter
    # geometries especially) must not land inside the timed run
    _fleet(args, "colocated", model, reqs, expected, warm=True)
    _fleet(args, "disaggregated", model, reqs, expected, warm=True)
    colo = _fleet(args, "colocated", model, reqs, expected)
    disagg = _fleet(args, "disaggregated", model, reqs, expected)
    reprefill += disagg["reprefill_tokens"]
    ratio = disagg["tpot_p99_ms"] / colo["tpot_p99_ms"]

    print(f"\ndisagg bench: {args.shorts} short "
          f"(prompt {args.short_prompt} + {args.short_budget}) + "
          f"{args.longs} long (prompt {args.long_prompt} + "
          f"{args.long_budget}), chunk {args.chunk}, 2 replicas "
          f"either way")
    hdr = (f"{'fleet':<14} {'tpot p50 ms':>12} {'tpot p99 ms':>12} "
           f"{'ttft p50 ms':>12} {'handoffs':>9} {'re-prefill':>11} "
           f"{'wall s':>7}")
    print(hdr)
    print("-" * len(hdr))
    for m in (colo, disagg):
        print(f"{m['mode']:<14} {m['tpot_p50_ms']:>12.2f} "
              f"{m['tpot_p99_ms']:>12.2f} {m['ttft_p50_ms']:>12.1f} "
              f"{m['handoffs']:>9} {m['reprefill_tokens']:>11} "
              f"{m['wall_s']:>7.1f}")
    print(f"decode TPOT p99 ratio (disagg/colocated): {ratio:.3f}  "
          f"[{disagg['handoffs']} handoffs, "
          f"{disagg['fallbacks']} fallbacks, "
          f"{disagg['steady_decode_ticks']} steady decode ticks all "
          f"{{'decode': 1}}]")
    print(f"re-prefilled tokens across every handoff phase: "
          f"{reprefill}")
    assert reprefill == 0, f"re-prefilled {reprefill} tokens"

    if args.track:
        import bench_track
        r = bench_track.append_round(
            {"metric": "disagg_decode_tpot_p99_ratio",
             "value": round(ratio, 4), "unit": "ratio",
             "note": f"short-stream decode TPOT p99 "
                     f"{disagg['tpot_p99_ms']:.2f} ms disaggregated "
                     f"vs {colo['tpot_p99_ms']:.2f} ms colocated at "
                     f"equal replica count "
                     f"({disagg['handoffs']} handoffs, "
                     f"{disagg['fallbacks']} fallbacks)"})
        print(f"tracked {r['metric']} = {r['value']}")
        r2 = bench_track.append_round(
            {"metric": "disagg_handoff_reprefill_tokens",
             "value": int(reprefill), "unit": "tokens",
             "note": "tokens prefilled twice across every handoff "
                     "phase (greedy + sampled parity, cross-topology, "
                     "disaggregated fleet) — the handoff path must "
                     "keep this at exactly 0"})
        print(f"tracked {r2['metric']} = {r2['value']}")
    return {"colocated": colo, "disaggregated": disagg,
            "ratio": ratio, "reprefill_tokens": int(reprefill)}


if __name__ == "__main__":
    main()
