#!/usr/bin/env python
"""Bench-round tracker: schema'd appends to BENCHLOG.jsonl plus a
tolerance-band regression gate (ISSUE 13).

BENCHLOG.jsonl is the repo's bench trajectory — one JSON object per
recorded round — but nothing used to validate what landed there or
notice when a recorded number fell off a cliff. This tool closes both
gaps:

- ``append`` validates a round against THE schema (required
  ``metric``/``value``/``unit``, optional ``vs_baseline``/``note``/
  ``ts``; unknown keys rejected, values type- and finiteness-checked,
  ``ts`` auto-stamped ISO-8601 UTC when absent) and appends one line.
  Benches call the library form (``append_round``) so every entry is
  schema-clean by construction.
- ``check`` (also spelled ``--check``) reads the LATEST round per
  metric and compares it against the committed tolerance bands in
  ``scripts/bench_bands.json`` (``{metric: {"min": .., "max": ..,
  "note": ..}}``; either bound optional). A banded metric that is
  missing from the log, out of band, or sitting on a malformed line
  exits 1 and names the offender — the bench trajectory is a
  regression GATE, not a scrapbook. Metrics without bands pass
  through (benches may record freely; promotion to a band is a
  deliberate commit).

The check validates the COMMITTED log against the COMMITTED bands — a
pure file check, deterministic in CI, no bench re-run. Recording a new
round that regresses a banded metric is what flips the gate.

Usage:
    python scripts/bench_track.py append --metric paged_decode_mfu \
        --value 0.017 --unit ratio [--note "..."] [--vs-baseline 1.1]
    python scripts/bench_track.py check          # or: --check
    python scripts/bench_track.py check --log BENCHLOG.jsonl \
        --bands scripts/bench_bands.json
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_LOG = os.path.join(REPO, "BENCHLOG.jsonl")
DEFAULT_BANDS = os.path.join(REPO, "scripts", "bench_bands.json")

REQUIRED_KEYS = ("metric", "value", "unit")
OPTIONAL_KEYS = ("ts", "vs_baseline", "note")
ALLOWED_KEYS = frozenset(REQUIRED_KEYS + OPTIONAL_KEYS)


class BenchLogError(ValueError):
    """A round or log line that violates the BENCHLOG schema, or a
    band check that cannot even be evaluated (malformed files fail
    the gate loudly, never silently pass)."""


def _utc_now_iso():
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def validate_round(round_dict):
    """Normalize + validate one bench round. Returns a NEW dict in
    stable key order with ``ts`` stamped if absent; raises
    ``BenchLogError`` naming the first violation."""
    if not isinstance(round_dict, dict):
        raise BenchLogError(f"round must be a dict, got "
                            f"{type(round_dict).__name__}")
    unknown = set(round_dict) - ALLOWED_KEYS
    if unknown:
        raise BenchLogError(
            f"unknown round key(s) {sorted(unknown)} — allowed: "
            f"{sorted(ALLOWED_KEYS)}")
    for k in REQUIRED_KEYS:
        if k not in round_dict:
            raise BenchLogError(f"round missing required key {k!r}")
    metric = round_dict["metric"]
    if not isinstance(metric, str) or not metric \
            or not all(c.isascii() and (c.isalnum() or c == "_")
                       for c in metric):
        raise BenchLogError(
            f"metric must be a nonempty [A-Za-z0-9_] string, got "
            f"{metric!r}")
    value = round_dict["value"]
    if isinstance(value, bool) or not isinstance(value, (int, float)) \
            or not math.isfinite(value):
        raise BenchLogError(f"value must be a finite number, got "
                            f"{value!r}")
    unit = round_dict["unit"]
    if not isinstance(unit, str) or not unit:
        raise BenchLogError(f"unit must be a nonempty string, got "
                            f"{unit!r}")
    out = {"metric": metric, "value": float(value), "unit": unit}
    vs = round_dict.get("vs_baseline")
    if vs is not None:
        if isinstance(vs, bool) or not isinstance(vs, (int, float)) \
                or not math.isfinite(vs):
            raise BenchLogError(f"vs_baseline must be a finite number, "
                                f"got {vs!r}")
        out["vs_baseline"] = float(vs)
    ts = round_dict.get("ts")
    if ts is None:
        ts = _utc_now_iso()
    elif not isinstance(ts, str) or not ts:
        raise BenchLogError(f"ts must be an ISO-8601 string, got {ts!r}")
    out["ts"] = ts
    note = round_dict.get("note")
    if note is not None:
        if not isinstance(note, str):
            raise BenchLogError(f"note must be a string, got {note!r}")
        out["note"] = note
    return out


def append_round(round_dict, path=DEFAULT_LOG):
    """Validate ``round_dict`` and append it as one JSONL line.
    Returns the normalized round actually written."""
    r = validate_round(round_dict)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(r) + "\n")
    return r


def load_rounds(path=DEFAULT_LOG):
    """Every round in the log, oldest first, schema-validated.
    A malformed line raises ``BenchLogError`` with its line number —
    the check must fail loudly on a corrupt log."""
    rounds = []
    if not os.path.exists(path):
        return rounds
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise BenchLogError(
                    f"{path}:{i}: not valid JSON ({e})") from e
            try:
                rounds.append(validate_round(obj))
            except BenchLogError as e:
                raise BenchLogError(f"{path}:{i}: {e}") from e
    return rounds


def load_bands(path=DEFAULT_BANDS):
    """``{metric: {"min"?: float, "max"?: float, "note"?: str}}``."""
    with open(path, encoding="utf-8") as f:
        bands = json.load(f)
    if not isinstance(bands, dict):
        raise BenchLogError(f"{path}: bands file must be a JSON object")
    for metric, band in bands.items():
        if not isinstance(band, dict):
            raise BenchLogError(f"{path}: band for {metric!r} must be "
                                f"an object")
        unknown = set(band) - {"min", "max", "note"}
        if unknown:
            raise BenchLogError(f"{path}: band for {metric!r} has "
                                f"unknown key(s) {sorted(unknown)}")
        if "min" not in band and "max" not in band:
            raise BenchLogError(f"{path}: band for {metric!r} needs "
                                f"min and/or max")
        for bound in ("min", "max"):
            v = band.get(bound)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v)):
                raise BenchLogError(
                    f"{path}: band for {metric!r}: {bound} must be a "
                    f"finite number, got {v!r}")
    return bands


def check(log_path=DEFAULT_LOG, bands_path=DEFAULT_BANDS):
    """Gate the log against the bands: the LATEST round of every
    banded metric must exist and sit inside its band. Returns
    ``(ok, [report lines])``."""
    report = []
    try:
        rounds = load_rounds(log_path)
        bands = load_bands(bands_path)
    except (BenchLogError, OSError, json.JSONDecodeError) as e:
        return False, [f"FAIL {e}"]
    latest = {}
    for r in rounds:                       # file order; last wins
        latest[r["metric"]] = r
    ok = True
    for metric in sorted(bands):
        band = bands[metric]
        r = latest.get(metric)
        if r is None:
            ok = False
            report.append(f"FAIL {metric}: banded but never recorded "
                          f"in {os.path.basename(log_path)}")
            continue
        lo, hi = band.get("min"), band.get("max")
        v = r["value"]
        if lo is not None and v < lo:
            ok = False
            report.append(f"FAIL {metric}: {v} < min {lo} "
                          f"(round ts={r['ts']})")
        elif hi is not None and v > hi:
            ok = False
            report.append(f"FAIL {metric}: {v} > max {hi} "
                          f"(round ts={r['ts']})")
        else:
            band_s = f"[{lo if lo is not None else '-inf'}, " \
                     f"{hi if hi is not None else '+inf'}]"
            report.append(f"ok   {metric}: {v} in {band_s}")
    return ok, report


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # `--check` is the documented short spelling of the subcommand
    if argv and argv[0] == "--check":
        argv[0] = "check"
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_add = sub.add_parser("append", help="validate + append one round")
    ap_add.add_argument("--metric", required=True)
    ap_add.add_argument("--value", type=float, required=True)
    ap_add.add_argument("--unit", required=True)
    ap_add.add_argument("--vs-baseline", type=float, default=None)
    ap_add.add_argument("--note", default=None)
    ap_add.add_argument("--log", default=DEFAULT_LOG)
    ap_chk = sub.add_parser("check", help="gate the log against the "
                                          "committed bands")
    ap_chk.add_argument("--log", default=DEFAULT_LOG)
    ap_chk.add_argument("--bands", default=DEFAULT_BANDS)
    args = ap.parse_args(argv)

    if args.cmd == "append":
        try:
            r = append_round({"metric": args.metric, "value": args.value,
                              "unit": args.unit,
                              "vs_baseline": args.vs_baseline,
                              "note": args.note}, path=args.log)
        except BenchLogError as e:
            print(f"FAIL {e}", file=sys.stderr)
            return 1
        print(f"appended {json.dumps(r)}")
        return 0
    ok, report = check(log_path=args.log, bands_path=args.bands)
    for line in report:
        print(line)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
