#!/usr/bin/env python
"""Lint: every metric registered in code is documented in README.md.

Metric names are an OPERATOR interface — dashboards, alerts, and the
capacity-planning runbook key on them — but they are registered as
string literals scattered through the codebase, so nothing used to
stop a PR from adding ``server_foo_total`` while the README metric
table quietly went stale (ISSUE 10: PR 7's ``router_orphaned_total``
and the whole ``scheduler_*`` family had already drifted). This lint
closes the loop: it extracts every name passed to
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")`` under
``paddle_tpu/`` and fails unless each appears verbatim somewhere in
README.md.

The check is direction-sensitive on purpose: code -> README only.
(README may legitimately mention historical or planned names; a
registered-but-undocumented metric is the drift that bites during an
incident.) Dynamic names (a variable instead of a literal) are
invisible to the scan — keep metric names literal, which the registry
API already encourages.

Usage: python scripts/check_metric_docs.py [--list]
Exit status 1 lists every undocumented metric. Wired into the test
suite (tests/test_flight_recorder.py) alongside check_no_bare_except,
so drift fails tier-1.
"""
from __future__ import annotations

import os
import re
import sys

# .counter( / .gauge( / .histogram( with a literal first argument,
# newline-tolerant (registrations routinely wrap the name)
_REG = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"']([A-Za-z_:][A-Za-z0-9_:]*)[\"']",
    re.S)

# metric names that are registered by BENCH/test scaffolding living
# inside the scanned tree, not part of the operator interface
IGNORED = frozenset()


def registered_metrics(root):
    """{name: [relpath, ...]} of literal metric registrations under
    ``root``."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                src = f.read()
            for m in _REG.finditer(src):
                name = m.group(1)
                if name not in IGNORED:
                    out.setdefault(name, []).append(
                        os.path.relpath(path, os.path.dirname(root)))
    return out


def undocumented(metrics, readme_text):
    """[(name, [paths])] of registered metrics README never mentions."""
    return sorted((name, paths) for name, paths in metrics.items()
                  if name not in readme_text)


def main(argv=None):
    argv = sys.argv if argv is None else argv
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    metrics = registered_metrics(os.path.join(repo, "paddle_tpu"))
    with open(os.path.join(repo, "README.md"), "r",
              encoding="utf-8") as f:
        readme = f.read()
    if "--list" in argv[1:]:
        for name in sorted(metrics):
            print(name)
        return 0
    missing = undocumented(metrics, readme)
    for name, paths in missing:
        print(f"{name}: registered in {', '.join(sorted(set(paths)))} "
              f"but never mentioned in README.md — add it to the "
              f"metric table (or rename the metric back)")
    if missing:
        return 1
    print(f"OK: all {len(metrics)} registered metric names are "
          f"documented in README.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
