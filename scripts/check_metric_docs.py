#!/usr/bin/env python
"""Lint: every metric registered in code is documented in README.md.

Metric names are an OPERATOR interface — dashboards, alerts, and the
capacity-planning runbook key on them — but they are registered as
string literals scattered through the codebase, so nothing used to
stop a PR from adding ``server_foo_total`` while the README metric
table quietly went stale (ISSUE 10: PR 7's ``router_orphaned_total``
and the whole ``scheduler_*`` family had already drifted). This lint
closes the loop: it extracts every name passed to
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")`` under
``paddle_tpu/`` and fails unless each appears verbatim somewhere in
README.md.

The check is direction-sensitive on purpose: code -> README only.
(README may legitimately mention historical or planned names; a
registered-but-undocumented metric is the drift that bites during an
incident.) Dynamic names (a variable instead of a literal) are
invisible to the scan — keep metric names literal, which the registry
API already encourages.

LABELS are part of the interface too (ISSUE 11): a dashboard keying on
``server_tokens_total{kind=...}`` breaks just as hard when the label
set drifts as when the name does. The scan therefore also extracts
each registration's declared ``labelnames=(...)`` and fails unless
README documents the metric with a brace group covering every label —
i.e. some ``metric_name{...}`` occurrence whose braces mention each
declared label name (``{kind}``, ``{kind=goodput|...}`` and multi-line
groups all count).

Usage: python scripts/check_metric_docs.py [--list]
Exit status 1 lists every undocumented metric (or label). Wired into
the test suite (tests/test_flight_recorder.py) alongside
check_no_bare_except, so drift fails tier-1.
"""
from __future__ import annotations

import os
import re
import sys

# .counter( / .gauge( / .histogram( with a literal first argument,
# newline-tolerant (registrations routinely wrap the name)
_REG = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"']([A-Za-z_:][A-Za-z0-9_:]*)[\"']",
    re.S)

# declared label names inside one registration's trailing window
_LABELNAMES = re.compile(r"labelnames\s*=\s*\(([^)]*)\)", re.S)
_QUOTED = re.compile(r"[\"']([A-Za-z_][A-Za-z0-9_]*)[\"']")
_WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")

# metric names that are registered by BENCH/test scaffolding living
# inside the scanned tree, not part of the operator interface
IGNORED = frozenset()


def _scan(root):
    """One walk over ``root``: ({name: [relpath, ...]},
    {name: sorted labelnames}) for every literal registration. The
    labelnames window for one registration runs to the NEXT
    registration call so a label-less metric can never borrow its
    neighbour's labels."""
    metrics, labels = {}, {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                src = f.read()
            regs = list(_REG.finditer(src))
            for i, m in enumerate(regs):
                name = m.group(1)
                if name in IGNORED:
                    continue
                metrics.setdefault(name, []).append(
                    os.path.relpath(path, os.path.dirname(root)))
                end = regs[i + 1].start() if i + 1 < len(regs) \
                    else len(src)
                lm = _LABELNAMES.search(src, m.end(), end)
                if lm is not None:
                    declared = _QUOTED.findall(lm.group(1))
                    if declared:
                        labels.setdefault(name, set()).update(declared)
    return metrics, {n: sorted(ls) for n, ls in labels.items()}


def registered_metrics(root):
    """{name: [relpath, ...]} of literal metric registrations under
    ``root``."""
    return _scan(root)[0]


def undocumented(metrics, readme_text):
    """[(name, [paths])] of registered metrics README never mentions."""
    return sorted((name, paths) for name, paths in metrics.items()
                  if name not in readme_text)


def registered_labels(root):
    """{name: sorted labelnames} for every literal registration that
    declares labels (see ``_scan`` for the window rule)."""
    return _scan(root)[1]


def undocumented_labels(labels_by_metric, readme_text):
    """[(name, [missing labels])] for labeled metrics README documents
    without their labels. A metric passes when SOME ``name{...}``
    occurrence's brace group mentions every declared label name
    (``{kind}``, ``{kind=a|b}``, wrapped groups all count)."""
    bad = []
    for name, labels in sorted(labels_by_metric.items()):
        best_missing = labels
        for m in re.finditer(re.escape(name) + r"\{([^}]*)\}",
                             readme_text):
            doc = set(_WORD.findall(m.group(1)))
            missing = [l for l in labels if l not in doc]  # noqa: E741
            if len(missing) < len(best_missing):
                best_missing = missing
            if not missing:
                break
        if best_missing:
            bad.append((name, best_missing))
    return bad


def main(argv=None):
    argv = sys.argv if argv is None else argv
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    metrics, labels = _scan(os.path.join(repo, "paddle_tpu"))
    with open(os.path.join(repo, "README.md"), "r",
              encoding="utf-8") as f:
        readme = f.read()
    if "--list" in argv[1:]:
        for name in sorted(metrics):
            print(name)
        return 0
    missing = undocumented(metrics, readme)
    for name, paths in missing:
        print(f"{name}: registered in {', '.join(sorted(set(paths)))} "
              f"but never mentioned in README.md — add it to the "
              f"metric table (or rename the metric back)")
    documented = {n for n in labels if n not in dict(missing)}
    label_drift = undocumented_labels(
        {n: labels[n] for n in documented}, readme)
    for name, miss in label_drift:
        print(f"{name}: declares labels {labels[name]} but no "
              f"{name}{{...}} occurrence in README.md mentions "
              f"{miss} — document the metric WITH its labels "
              f"(e.g. `{name}{{{miss[0]}}}`)")
    if missing or label_drift:
        return 1
    print(f"OK: all {len(metrics)} registered metric names "
          f"({len(labels)} labeled) are documented in README.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
