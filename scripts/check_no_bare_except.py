#!/usr/bin/env python
"""Lint: no bare ``except:`` clauses in paddle_tpu/, benchmarks/, or
scripts/.

A bare except swallows KeyboardInterrupt/SystemExit and — worse for a
reliability layer — erases the TYPE of the failure, which is the whole
contract (clients branch on ``ReliabilityError`` subclasses; the chaos
suites assert on them). ``except Exception`` is the floor. Benchmarks
and tooling are covered too: a bench that swallows its own failure
reports numbers for work that never ran.

Usage: python scripts/check_no_bare_except.py [root ...]
Exit status 1 lists every offending file:line. Wired into the test
suite (tests/test_train_reliability.py) so a regression fails tier-1.
"""
from __future__ import annotations

import ast
import os
import sys


def bare_excepts(root):
    """[(path, lineno), ...] of bare ``except:`` handlers under root."""
    hits = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "rb") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                hits.append((path, e.lineno or 0))
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ExceptHandler) and node.type is None:
                    hits.append((path, node.lineno))
    return hits


DEFAULT_DIRS = ("paddle_tpu", "benchmarks", "scripts")


def main(argv):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = argv[1:] or [os.path.join(repo, d) for d in DEFAULT_DIRS]
    hits = []
    for root in roots:
        hits += bare_excepts(root)
    for path, line in hits:
        print(f"{path}:{line}: bare 'except:' — name the exception type "
              "(at least 'except Exception')")
    if hits:
        return 1
    print(f"OK: no bare excepts under {', '.join(roots)}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
