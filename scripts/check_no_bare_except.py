#!/usr/bin/env python
"""Lint: no bare ``except:`` clauses inside paddle_tpu/.

A bare except swallows KeyboardInterrupt/SystemExit and — worse for a
reliability layer — erases the TYPE of the failure, which is the whole
contract (clients branch on ``ReliabilityError`` subclasses; the chaos
suites assert on them). ``except Exception`` is the floor.

Usage: python scripts/check_no_bare_except.py [root]
Exit status 1 lists every offending file:line. Wired into the test
suite (tests/test_train_reliability.py) so a regression fails tier-1.
"""
from __future__ import annotations

import ast
import os
import sys


def bare_excepts(root):
    """[(path, lineno), ...] of bare ``except:`` handlers under root."""
    hits = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "rb") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                hits.append((path, e.lineno or 0))
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ExceptHandler) and node.type is None:
                    hits.append((path, node.lineno))
    return hits


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "paddle_tpu")
    hits = bare_excepts(root)
    for path, line in hits:
        print(f"{path}:{line}: bare 'except:' — name the exception type "
              "(at least 'except Exception')")
    if hits:
        return 1
    print(f"OK: no bare excepts under {root}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
