#!/usr/bin/env python
"""Lint: no bare ``except:`` clauses in paddle_tpu/, benchmarks/, or
scripts/ — and, in the serving stack, no silent scope cuts.

A bare except swallows KeyboardInterrupt/SystemExit and — worse for a
reliability layer — erases the TYPE of the failure, which is the whole
contract (clients branch on ``ReliabilityError`` subclasses; the chaos
suites assert on them). ``except Exception`` is the floor. Benchmarks
and tooling are covered too: a bench that swallows its own failure
reports numbers for work that never ran.

Scope-cut rule (ISSUE 6, dirs extended to reliability/ + telemetry/ by
ISSUE 7): under the serving/kernel/reliability dirs
(``SCOPE_CUT_DIRS``), every ``raise NotImplementedError("...")`` WITH a
message must point at the ROADMAP item that will lift it (the string
contains "ROADMAP") — that is what kept the paged+mesh and paged+int8
cuts discoverable instead of buried. Deliberate non-cuts (abstract
methods raise bare; API refusals) opt out with a ``# no-roadmap:
<reason>`` comment on the raise line, which is itself grep-able.

Required-cut rule (ISSUE 8): some dispatch sites must KEEP a
ROADMAP-pointered refusal — ``REQUIRED_CUTS`` lists (file, keyword)
pairs, and the lint fails if the file no longer contains a pointered
``NotImplementedError`` mentioning the keyword — silently "supporting"
a combo, or deleting a refusal wholesale, is exactly the kind of quiet
contract change this lint exists to surface. Lifting a cut for real
(as ISSUE 16 did for paged+mesh) means removing its entry here in the
same change that makes the combo work.

Usage: python scripts/check_no_bare_except.py [root ...]
Exit status 1 lists every offending file:line. Wired into the test
suite (tests/test_train_reliability.py) so a regression fails tier-1.
"""
from __future__ import annotations

import ast
import os
import sys

DEFAULT_DIRS = ("paddle_tpu", "benchmarks", "scripts")

# serving/kernel surfaces where a NotImplementedError is (almost
# always) a recorded scope cut — the ROADMAP is its tracking issue.
# reliability/ and telemetry/ joined with the multi-replica router
# (ISSUE 7): scope cuts in the supervisor/failover machinery are
# exactly the kind that silently bite during an incident.
SCOPE_CUT_DIRS = (
    os.path.join("paddle_tpu", "inference"),
    os.path.join("paddle_tpu", "models"),
    os.path.join("paddle_tpu", "ops", "pallas"),
    os.path.join("paddle_tpu", "reliability"),
    os.path.join("paddle_tpu", "telemetry"),
)
OPT_OUT = "no-roadmap:"

# dispatch sites that must KEEP a ROADMAP-pointered
# NotImplementedError: (repo-relative file, keyword its message must
# mention). ISSUE 8: the optimistic-admission mode dispatch — the
# optimistic+dense combo must refuse with a pointer, not silently
# half-work or lose its annotation. ISSUE 14: the fused serving tick
# runs ONE decode row per slot — tick_block > 1 is the speculative
# multi-token verify shape (ROADMAP item 6) and must refuse with a
# pointer until that lands. (ISSUE 14 LIFTED the PR-6 skipped-page-DMA
# and null-redirect cuts for serving_mode="fused"; the split kernels
# keep them as the documented baseline, no refusal site involved.)
# ISSUE 16 LIFTED the paged+mesh cut (the pool now shards on the
# kv-head dim over the mp axis) and left two pointered refusals in its
# place: the int8 paged pool (generation.py, ROADMAP item 3) and the
# fused tick on a mesh (continuous_batching.py, ROADMAP item 2 — the
# megakernel's DMA schedule and sampling epilogue are still
# single-device; split mode serves meshes). ISSUE 20 LIFTED the
# pre-first-token migrate_out refusal (an empty-``emitted`` migration
# IS a prefill->decode handoff now) and points the next cut instead:
# disaggregated placement stops at one datacenter's flat network —
# placement="cross-datacenter" (bandwidth-aware frame scheduling,
# ROADMAP item 4 follow-on) must refuse with a pointer until it lands.
REQUIRED_CUTS = (
    (os.path.join("paddle_tpu", "models", "generation.py"),
     "int8"),
    (os.path.join("paddle_tpu", "inference", "continuous_batching.py"),
     "optimistic"),
    (os.path.join("paddle_tpu", "inference", "continuous_batching.py"),
     "tick_block"),
    (os.path.join("paddle_tpu", "inference", "continuous_batching.py"),
     "fused+mesh"),
    (os.path.join("paddle_tpu", "inference", "placement.py"),
     "cross-datacenter"),
)


def _raise_strings(node):
    """String-literal fragments inside a ``raise NotImplementedError``
    call's arguments (f-strings contribute their constant parts)."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return out


def _unpointered_not_implemented(tree, lines, path):
    """[(path, lineno), ...] of messageful NotImplementedError raises
    with no ROADMAP pointer and no ``# no-roadmap:`` opt-out."""
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        if not (isinstance(exc, ast.Call)
                and isinstance(exc.func, ast.Name)
                and exc.func.id == "NotImplementedError"):
            continue
        strings = _raise_strings(exc)
        if not strings:
            continue                      # bare/dynamic message: skip
        if any("ROADMAP" in s for s in strings):
            continue
        start = node.lineno - 1
        end = getattr(node, "end_lineno", node.lineno)
        if any(OPT_OUT in lines[i] for i in
               range(max(0, start - 1), min(end, len(lines)))):
            continue
        hits.append((path, node.lineno))
    return hits


def scan(root, repo):
    """(bare_excepts, unpointered_cuts) under ``root``."""
    bare, cuts = [], []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "rb") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                bare.append((path, e.lineno or 0))
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ExceptHandler) \
                        and node.type is None:
                    bare.append((path, node.lineno))
            rel = os.path.relpath(path, repo)
            if any(rel.startswith(d + os.sep) or rel == d
                   for d in SCOPE_CUT_DIRS):
                lines = src.decode("utf-8",
                                   errors="replace").splitlines()
                cuts += _unpointered_not_implemented(tree, lines, path)
    return bare, cuts


def bare_excepts(root):
    """[(path, lineno), ...] of bare ``except:`` handlers under root
    (kept for existing callers)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return scan(root, repo)[0]


def missing_required_cuts(repo):
    """[(relpath, keyword), ...] of ``REQUIRED_CUTS`` entries whose
    file no longer holds a ROADMAP-pointered ``NotImplementedError``
    mentioning the keyword (or cannot be parsed)."""
    missing = []
    for rel, keyword in REQUIRED_CUTS:
        path = os.path.join(repo, rel)
        try:
            with open(path, "rb") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            missing.append((rel, keyword))
            continue
        found = False
        for node in ast.walk(tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if not (isinstance(exc, ast.Call)
                    and isinstance(exc.func, ast.Name)
                    and exc.func.id == "NotImplementedError"):
                continue
            strings = _raise_strings(exc)
            if any("ROADMAP" in s for s in strings) \
                    and any(keyword in s for s in strings):
                found = True
                break
        if not found:
            missing.append((rel, keyword))
    return missing


def main(argv):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    roots = argv[1:] or [os.path.join(repo, d) for d in DEFAULT_DIRS]
    bare, cuts = [], []
    for root in roots:
        b, c = scan(root, repo)
        bare += b
        cuts += c
    # positive obligations are repo-level, independent of which roots
    # were passed (a partial run must not skip them)
    required = missing_required_cuts(repo)
    for path, line in bare:
        print(f"{path}:{line}: bare 'except:' — name the exception type "
              "(at least 'except Exception')")
    for path, line in cuts:
        print(f"{path}:{line}: NotImplementedError without a ROADMAP "
              "pointer — name the ROADMAP item that lifts this scope "
              f"cut, or opt out with '# {OPT_OUT} <reason>'")
    for rel, keyword in required:
        print(f"{rel}: required scope cut missing — expected a "
              f"ROADMAP-pointered NotImplementedError mentioning "
              f"{keyword!r} (see REQUIRED_CUTS)")
    if bare or cuts or required:
        return 1
    print(f"OK: no bare excepts / unpointered scope cuts under "
          f"{', '.join(roots)}; {len(REQUIRED_CUTS)} required cut(s) "
          f"present")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
